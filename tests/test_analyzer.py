"""delta-lint (delta_tpu.tools.analyzer) fixture tests.

Every rule gets a positive fixture (the rule must fire) and a negative
fixture (the rule must stay silent), exercised through
``analyze_sources`` so nothing touches disk. The error-catalog rules
run against a temp catalog via the ``DELTA_LINT_CATALOG`` override.
The final test is the tier-1 gate: the analyzer over the installed
``delta_tpu`` package must report ZERO unsuppressed findings.
"""

from __future__ import annotations

import json
import os

import pytest

from delta_tpu.tools.analyzer import analyze_paths, analyze_sources
from delta_tpu.tools.analyzer.cli import main as lint_main
from delta_tpu.tools.analyzer.core import all_rules
from delta_tpu.tools.analyzer.report import render_json
from delta_tpu.tools.analyzer.suppress import parse_suppressions


def _rules_fired(report, rule_id):
    return [f for f in report.findings if f.rule == rule_id]


# ------------------------------------------------------------- lock-order


def test_lock_order_cycle_detected():
    src = """
import threading
A = threading.Lock()
B = threading.Lock()

def ab():
    with A:
        with B:
            pass

def ba():
    with B:
        with A:
            pass
"""
    report = analyze_sources({"m.py": src}, rules=["lock-order"])
    found = _rules_fired(report, "lock-order")
    assert found, "opposite-order acquisition must be flagged"
    assert any("cycle" in f.message for f in found)


def test_lock_order_consistent_is_clean():
    src = """
import threading
A = threading.Lock()
B = threading.Lock()

def ab():
    with A:
        with B:
            pass

def ab2():
    with A:
        with B:
            pass
"""
    report = analyze_sources({"m.py": src}, rules=["lock-order"])
    assert not _rules_fired(report, "lock-order")


def test_lock_order_self_deadlock_direct():
    src = """
import threading
L = threading.Lock()

def f():
    with L:
        with L:
            pass
"""
    report = analyze_sources({"m.py": src}, rules=["lock-order"])
    assert any("self-deadlock" in f.message
               for f in _rules_fired(report, "lock-order"))


def test_lock_order_self_deadlock_through_call():
    src = """
import threading
L = threading.Lock()

def inner():
    with L:
        pass

def outer():
    with L:
        inner()
"""
    report = analyze_sources({"m.py": src}, rules=["lock-order"])
    found = _rules_fired(report, "lock-order")
    assert any("inner" in f.message and "self-deadlock" in f.message
               for f in found)


def test_lock_order_rlock_reentry_allowed():
    src = """
import threading
L = threading.RLock()

def f():
    with L:
        with L:
            pass
"""
    report = analyze_sources({"m.py": src}, rules=["lock-order"])
    assert not _rules_fired(report, "lock-order")


# --------------------------------------------------------------- lock-io


def test_lock_io_direct():
    src = """
import threading
L = threading.Lock()

def f(path):
    with L:
        with open(path) as fh:
            return fh.read()
"""
    report = analyze_sources({"m.py": src}, rules=["lock-io"])
    assert any("open" in f.message
               for f in _rules_fired(report, "lock-io"))


def test_lock_io_through_helper_call():
    src = """
import os
import threading
L = threading.Lock()

def helper(path):
    os.unlink(path)

def f(path):
    with L:
        helper(path)
"""
    report = analyze_sources({"m.py": src}, rules=["lock-io"])
    assert any("helper" in f.message
               for f in _rules_fired(report, "lock-io"))


def test_lock_io_outside_lock_is_clean():
    src = """
import threading
L = threading.Lock()

def f(path):
    with open(path) as fh:
        data = fh.read()
    with L:
        return data
"""
    report = analyze_sources({"m.py": src}, rules=["lock-io"])
    assert not _rules_fired(report, "lock-io")


def test_lock_io_instance_lock():
    src = """
import threading

class C:
    def __init__(self):
        self._lock = threading.Lock()

    def f(self, path):
        with self._lock:
            return open(path).read()
"""
    report = analyze_sources({"m.py": src}, rules=["lock-io"])
    assert _rules_fired(report, "lock-io")


# ------------------------------------------------------- global-mutation


def test_global_mutation_outside_lock():
    src = """
import threading
L = threading.Lock()
CACHE = {}

def put(k, v):
    CACHE[k] = v
"""
    report = analyze_sources({"m.py": src}, rules=["global-mutation"])
    assert any("CACHE" in f.message
               for f in _rules_fired(report, "global-mutation"))


def test_global_mutation_under_lock_is_clean():
    src = """
import threading
L = threading.Lock()
CACHE = {}

def put(k, v):
    with L:
        CACHE[k] = v
"""
    report = analyze_sources({"m.py": src}, rules=["global-mutation"])
    assert not _rules_fired(report, "global-mutation")


def test_global_mutation_method_call():
    src = """
import threading
L = threading.Lock()
SEEN = set()

def mark(x):
    SEEN.add(x)
"""
    report = analyze_sources({"m.py": src}, rules=["global-mutation"])
    assert _rules_fired(report, "global-mutation")


def test_global_mutation_ignored_without_locks():
    # a lock-free module is single-threaded by convention: not flagged
    src = """
CACHE = {}

def put(k, v):
    CACHE[k] = v
"""
    report = analyze_sources({"m.py": src}, rules=["global-mutation"])
    assert not _rules_fired(report, "global-mutation")


# ------------------------------------------------------------ jit purity


def test_jit_impure_clock_in_decorated():
    src = """
import time
import jax

@jax.jit
def kernel(x):
    return x * time.time()
"""
    report = analyze_sources({"m.py": src}, rules=["jit-impure"])
    assert any("time.time" in f.message
               for f in _rules_fired(report, "jit-impure"))


def test_jit_impure_reaches_helpers():
    src = """
import random
import jax

def helper(x):
    return x + random.random()

@jax.jit
def kernel(x):
    return helper(x)
"""
    report = analyze_sources({"m.py": src}, rules=["jit-impure"])
    assert any("random.random" in f.message
               for f in _rules_fired(report, "jit-impure"))


def test_jit_impure_call_form_and_partial_alias():
    src = """
import functools
import time
import jax

_fastjit = functools.partial(jax.jit, static_argnames=("n",))

@_fastjit
def kernel(x, n):
    return x + time.time_ns()

def plain(x):
    return jax.jit(inner)(x)

def inner(x):
    return time.perf_counter()
"""
    report = analyze_sources({"m.py": src}, rules=["jit-impure"])
    msgs = " ".join(f.message for f in _rules_fired(report, "jit-impure"))
    assert "time.time_ns" in msgs and "time.perf_counter" in msgs


def test_jit_impure_unreachable_function_is_clean():
    src = """
import time
import jax

@jax.jit
def kernel(x):
    return x + 1

def host_only():
    return time.time()
"""
    report = analyze_sources({"m.py": src}, rules=["jit-impure"])
    assert not _rules_fired(report, "jit-impure")


def test_jit_impure_nonlocal_mutation():
    src = """
import jax

def build():
    acc = 0
    @jax.jit
    def kernel(x):
        nonlocal acc
        acc += 1
        return x
    return kernel
"""
    report = analyze_sources({"m.py": src}, rules=["jit-impure"])
    assert any("nonlocal" in f.message
               for f in _rules_fired(report, "jit-impure"))


def test_jit_sync_item_and_block_until_ready():
    src = """
import jax

@jax.jit
def kernel(x):
    return x.sum().item()

def host(y):
    return y.block_until_ready()
"""
    report = analyze_sources({"m.py": src}, rules=["jit-sync"])
    msgs = " ".join(f.message for f in _rules_fired(report, "jit-sync"))
    assert ".item()" in msgs and "block_until_ready" in msgs


def test_jit_sync_item_outside_jit_is_clean():
    src = """
def host(x):
    return x.sum().item()
"""
    report = analyze_sources({"m.py": src}, rules=["jit-sync"])
    assert not _rules_fired(report, "jit-sync")


def test_jit_impure_shard_map_factory_body():
    # shard_map(make_kernel(...), ...) — the factory and the body it
    # returns are traced code, even without a jit decorator in sight
    src = """
import time
from jax.experimental.shard_map import shard_map

def make_kernel(width):
    def kernel(ops):
        return ops[0] * time.time()
    return kernel

def launch(mesh, ops):
    fn = shard_map(make_kernel(4), mesh=mesh, in_specs=None, out_specs=None)
    return fn(ops)
"""
    report = analyze_sources({"m.py": src}, rules=["jit-impure"])
    assert any("time.time" in f.message
               for f in _rules_fired(report, "jit-impure"))


def test_jit_impure_collective_marks_root():
    # a psum can only execute inside traced device code, so the
    # containing function gets purity rules with no visible wrapper
    src = """
import time
from jax import lax

def shard_body(x):
    total = lax.psum(x, "shard")
    return total + time.time()
"""
    report = analyze_sources({"m.py": src}, rules=["jit-impure"])
    assert any("time.time" in f.message
               for f in _rules_fired(report, "jit-impure"))


# ----------------------------------------------------------- error rules


_CATALOG_FIXTURE_SRC = """
class DeltaError(Exception):
    error_class = "DELTA_ERROR"

class FooError(DeltaError):
    error_class = "DELTA_FOO"

def raise_foo():
    raise FooError("boom")

def raise_typo():
    raise FooError("boom", error_class="DELTA_TYPO")

def raise_untyped():
    raise MysteryError("boom")
"""


@pytest.fixture()
def catalog_env(tmp_path, monkeypatch):
    path = tmp_path / "error_classes.json"
    path.write_text(json.dumps({
        "DELTA_ERROR": {"message": ["e"]},
        "DELTA_FOO": {"message": ["f"]},
        "DELTA_DEAD": {"message": ["d"]},
    }, indent=1))
    monkeypatch.setenv("DELTA_LINT_CATALOG", str(path))
    return path


def test_error_uncataloged_kwarg(catalog_env):
    report = analyze_sources({"m.py": _CATALOG_FIXTURE_SRC},
                             rules=["error-uncataloged"])
    found = _rules_fired(report, "error-uncataloged")
    assert any("DELTA_TYPO" in f.message for f in found)
    assert not any("DELTA_FOO" in f.message for f in found)


def test_error_dead_entry(catalog_env):
    report = analyze_sources({"m.py": _CATALOG_FIXTURE_SRC},
                             rules=["error-dead-entry"])
    found = _rules_fired(report, "error-dead-entry")
    assert any("DELTA_DEAD" in f.message for f in found)
    # DELTA_FOO is produced, DELTA_ERROR is the audited family root
    assert not any("DELTA_FOO" in f.message
                   or "'DELTA_ERROR'" in f.message for f in found)


def test_error_untyped_raise(catalog_env):
    report = analyze_sources({"m.py": _CATALOG_FIXTURE_SRC},
                             rules=["error-untyped-raise"])
    found = _rules_fired(report, "error-untyped-raise")
    assert any("MysteryError" in f.message for f in found)
    assert not any("FooError" in f.message for f in found)


def test_error_rules_allow_builtins_and_subclasses(catalog_env):
    src = """
class DeltaError(Exception):
    error_class = "DELTA_ERROR"

class Narrowed(DeltaError):
    pass

def f():
    raise ValueError("builtin ok")

def g():
    raise Narrowed("inherits an error_class ok")
"""
    report = analyze_sources({"m.py": src}, rules=["error-untyped-raise"])
    assert not _rules_fired(report, "error-untyped-raise")


# ---------------------------------------------------------- metric rules


_METRIC_FIXTURE_SRC = """
from delta_tpu import obs

_HITS = obs.counter("demo.hits")
_TYPO = obs.counter("demo.htis")
_DEPTH = obs.gauge("demo.depth")
_WRONG_KIND = obs.counter("demo.depth")
_DYNAMIC = obs.counter("demo." + suffix)
"""


@pytest.fixture()
def metric_catalog_env(tmp_path, monkeypatch):
    path = tmp_path / "metric_names.json"
    path.write_text(json.dumps({
        "counters": {"demo.hits": "Fixture hits.",
                     "demo.dead": "Fixture dead entry."},
        "histograms": {},
        "gauges": {"demo.depth": "Fixture depth."},
    }, indent=1))
    monkeypatch.setenv("DELTA_LINT_METRIC_CATALOG", str(path))
    return path


def test_metric_uncataloged(metric_catalog_env):
    report = analyze_sources({"m.py": _METRIC_FIXTURE_SRC},
                             rules=["metric-uncataloged"])
    found = _rules_fired(report, "metric-uncataloged")
    assert any("demo.htis" in f.message for f in found)
    # cataloged names under the right kind stay silent
    assert not any("demo.hits" in f.message for f in found)


def test_metric_uncataloged_kind_mismatch(metric_catalog_env):
    report = analyze_sources({"m.py": _METRIC_FIXTURE_SRC},
                             rules=["metric-uncataloged"])
    found = _rules_fired(report, "metric-uncataloged")
    mismatch = [f for f in found if "demo.depth" in f.message]
    assert mismatch and "cataloged as a gauge" in mismatch[0].message


def test_metric_dead_entry(metric_catalog_env):
    report = analyze_sources({"m.py": _METRIC_FIXTURE_SRC},
                             rules=["metric-dead-entry"])
    found = _rules_fired(report, "metric-dead-entry")
    assert any("demo.dead" in f.message for f in found)
    assert not any("demo.hits" in f.message for f in found)


def test_metric_rules_ignore_dynamic_names(metric_catalog_env):
    src = """
from delta_tpu import obs

def make(name):
    return obs.counter("demo." + name)
"""
    report = analyze_sources({"m.py": src}, rules=["metric-uncataloged"])
    assert not _rules_fired(report, "metric-uncataloged")


def test_metric_dead_entry_silent_without_sites(metric_catalog_env):
    # a scan over files with no instrument sites at all must not mark
    # the whole catalog dead (e.g. linting a single non-metric module)
    report = analyze_sources({"m.py": "def f():\n    return 1\n"},
                             rules=["metric-dead-entry"])
    assert not _rules_fired(report, "metric-dead-entry")


# ------------------------------------------------------- except hygiene


def test_except_swallow_flagged():
    src = """
def f():
    try:
        work()
    except Exception:
        pass
"""
    report = analyze_sources({"m.py": src}, rules=["except-swallow"])
    assert _rules_fired(report, "except-swallow")


def test_except_swallow_bare_except_flagged():
    src = """
def f():
    try:
        work()
    except:
        return None
"""
    report = analyze_sources({"m.py": src}, rules=["except-swallow"])
    assert _rules_fired(report, "except-swallow")


@pytest.mark.parametrize("body", [
    "raise",
    "log.warning('failed: %s', e)",
    "print(e)",
    "handle(e)",
])
def test_except_swallow_negative_forms(body):
    src = f"""
import logging
log = logging.getLogger(__name__)

def f():
    try:
        work()
    except Exception as e:
        {body}
"""
    report = analyze_sources({"m.py": src}, rules=["except-swallow"])
    assert not _rules_fired(report, "except-swallow")


def test_except_swallow_batch_member_outcome_shape():
    """Pins the group-commit batch-partitioning contract: a member's
    ConcurrentTransactionError must become a typed per-member outcome
    (used handler) — a broad except that silently drops it would turn
    a real conflict into a phantom commit. The GOOD shape mirrors
    `groupcommit._emit_inner`; the BAD shape (outcome assigned without
    using the exception) must be flagged."""
    good = """
def partition(batch, cs):
    for m in batch:
        try:
            cs.resolve(m.txn)
        except ConcurrentTransactionError as e:
            m.outcome = reject(e)
            continue
        m.outcome = accept(m)
"""
    report = analyze_sources({"m.py": good}, rules=["except-swallow"])
    assert not _rules_fired(report, "except-swallow")

    bad = """
def partition(batch, cs):
    for m in batch:
        try:
            cs.resolve(m.txn)
        except Exception:
            continue
        m.outcome = accept(m)
"""
    report = analyze_sources({"m.py": bad}, rules=["except-swallow"])
    assert _rules_fired(report, "except-swallow")


def test_except_swallow_narrow_type_is_clean():
    src = """
def f():
    try:
        work()
    except (OSError, ValueError):
        pass
"""
    report = analyze_sources({"m.py": src}, rules=["except-swallow"])
    assert not _rules_fired(report, "except-swallow")


def test_mutable_default_flagged():
    src = """
def f(x, acc=[]):
    acc.append(x)
    return acc

def g(*, opts={}):
    return opts

def h(s=set()):
    return s
"""
    report = analyze_sources({"m.py": src}, rules=["mutable-default"])
    assert len(_rules_fired(report, "mutable-default")) == 3


def test_mutable_default_none_is_clean():
    src = """
def f(x, acc=None, n=3, name="x", t=()):
    return acc
"""
    report = analyze_sources({"m.py": src}, rules=["mutable-default"])
    assert not _rules_fired(report, "mutable-default")


# --------------------------------------------------------- undefined-name


def test_undefined_name_flagged():
    src = """
def f(x):
    return missing_helper(x)
"""
    report = analyze_sources({"m.py": src}, rules=["undefined-name"])
    assert any("missing_helper" in f.message
               for f in _rules_fired(report, "undefined-name"))


def test_undefined_name_negative():
    src = """
import os

def helper(x):
    return x

def f(x):
    return helper(os.fspath(x)) + len([])
"""
    report = analyze_sources({"m.py": src}, rules=["undefined-name"])
    assert not _rules_fired(report, "undefined-name")


def test_undefined_name_star_import_skipped():
    src = """
from os.path import *

def f(x):
    return join(x, anything_at_all(x))
"""
    report = analyze_sources({"m.py": src}, rules=["undefined-name"])
    assert not _rules_fired(report, "undefined-name")


# ----------------------------------------------------------- suppression


def test_line_suppression():
    src = """
def f():
    try:
        work()
    except Exception:  # delta-lint: disable=except-swallow — audited
        pass
"""
    report = analyze_sources({"m.py": src}, rules=["except-swallow"])
    assert not report.findings
    assert len(report.suppressed) == 1
    assert report.suppressed[0].rule == "except-swallow"


def test_standalone_comment_suppresses_next_code_line():
    src = """
def f():
    try:
        work()
    # delta-lint: disable=except-swallow (audited: fixture —
    # rationale may span multiple comment lines)
    except Exception:
        pass
"""
    report = analyze_sources({"m.py": src}, rules=["except-swallow"])
    assert not report.findings and len(report.suppressed) == 1


def test_file_level_suppression_and_disable_all():
    src = """# delta-lint: file-disable=except-swallow
def f():
    try:
        work()
    except Exception:
        pass
"""
    report = analyze_sources({"m.py": src}, rules=["except-swallow"])
    assert not report.findings and report.suppressed

    per_line, file_level = parse_suppressions(
        "x = 1  # delta-lint: disable=all\n")
    assert "all" in per_line[1] and not file_level


def test_suppression_does_not_leak_to_other_rules():
    src = """
def f(acc=[]):
    try:
        work()
    except Exception:  # delta-lint: disable=jit-impure
        pass
"""
    report = analyze_sources({"m.py": src},
                             rules=["except-swallow", "mutable-default"])
    assert _rules_fired(report, "except-swallow")
    assert _rules_fired(report, "mutable-default")


def test_parse_error_reported():
    report = analyze_sources({"m.py": "def broken(:\n"})
    assert any(f.rule == "parse-error" for f in report.findings)


# ------------------------------------------------------------------- CLI


def test_cli_list_rules(capsys):
    assert lint_main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rule_id in ("lock-order", "lock-io", "jit-impure",
                    "error-uncataloged", "except-swallow",
                    "undefined-name"):
        assert rule_id in out


def test_cli_exit_codes(tmp_path, capsys):
    bad = tmp_path / "bad.py"
    bad.write_text("def f(x=[]):\n    return x\n")
    good = tmp_path / "good.py"
    good.write_text("def f(x=None):\n    return x\n")

    assert lint_main([str(good)]) == 0
    assert lint_main([str(bad)]) == 1
    assert lint_main([str(tmp_path / "missing.py")]) == 2
    assert lint_main([str(good), "--rules", "not-a-rule"]) == 2
    capsys.readouterr()


def test_cli_json_format(tmp_path, capsys):
    bad = tmp_path / "bad.py"
    bad.write_text("def f(x=[]):\n    return x\n")
    assert lint_main([str(bad), "--format", "json"]) == 1
    doc = json.loads(capsys.readouterr().out)
    results = doc["runs"][0]["results"]
    assert results and results[0]["ruleId"] == "mutable-default"
    assert doc["runs"][0]["summary"]["findings"] == len(results)


def test_render_json_roundtrip():
    report = analyze_sources({"m.py": "def f(x=[]):\n    return x\n"})
    doc = json.loads(render_json(report))
    assert doc["runs"][0]["tool"]["driver"]["name"] == "delta-lint"


def test_every_registered_rule_has_fixture_coverage():
    """Each of the analysis passes must be exercised above; this
    guards the registry against silently-unregistered rules."""
    expected = {
        "lock-order", "lock-io", "global-mutation",          # locks
        "jit-impure", "jit-sync",                            # purity
        "error-uncataloged", "error-dead-entry",
        "error-untyped-raise",                               # catalog
        "metric-uncataloged", "metric-dead-entry",           # metrics
        "except-swallow", "mutable-default",                 # hygiene
        "undefined-name",                                    # imports
        "obs-span-leak",                                     # obs
        "threadpool-discipline",                             # threads
        "retry-discipline",                                  # retry
        "handler-discipline",                                # serve
        "shared-state-race",                                 # races
        "transfer-budget", "transfer-unbudgeted",            # budget
        "unprofiled-dispatch",                               # device obs
        "resident-ledger-discipline",                        # hbm ledger
        "route-contract",                                    # routes
        "recompile-risk",                                    # recompile
        "env-knob-uncataloged", "env-knob-dead-entry",
        "env-knob-capture-stamp",                            # env census
    }
    assert set(all_rules()) == expected


# ----------------------------------------------------- obs-span-leak


def test_obs_span_leak_bare_call_flagged():
    src = """
from delta_tpu import obs

def load():
    s = obs.span("snapshot.load")  # never entered
    do_work()
    return s
"""
    report = analyze_sources({"m.py": src}, rules=["obs-span-leak"])
    found = _rules_fired(report, "obs-span-leak")
    assert len(found) == 1 and found[0].line == 5


def test_obs_span_leak_from_import_alias_flagged():
    src = """
from delta_tpu.obs import span as _span

def load():
    ctx = _span("snapshot.load")
    with ctx:
        pass
"""
    report = analyze_sources({"m.py": src}, rules=["obs-span-leak"])
    assert _rules_fired(report, "obs-span-leak"), \
        "span bound to a variable first is still a leak (parent is read " \
        "at __enter__, not at construction)"


def test_obs_span_leak_raw_perf_counter_flagged():
    src = """
import time
from delta_tpu import obs

def load():
    t0 = time.perf_counter_ns()
    with obs.span("snapshot.load"):
        pass
    return time.perf_counter_ns() - t0
"""
    report = analyze_sources({"m.py": src}, rules=["obs-span-leak"])
    assert len(_rules_fired(report, "obs-span-leak")) == 2


def test_obs_span_leak_negative():
    # with-statement spans and perf_counter_ns in UNinstrumented
    # modules are both fine
    clean = """
from delta_tpu import obs

def load():
    with obs.span("snapshot.load", table="/t") as sp:
        sp.set_attr("version", 3)
"""
    uninstrumented = """
import time

def bench():
    t0 = time.perf_counter_ns()
    return time.perf_counter_ns() - t0
"""
    report = analyze_sources(
        {"a.py": clean, "b.py": uninstrumented}, rules=["obs-span-leak"])
    assert not report.findings


def test_obs_span_leak_suppression_pragma():
    src = """
import time
from delta_tpu import obs

def measure():
    # delta-lint: disable=obs-span-leak
    t0 = time.perf_counter_ns()
    return t0
"""
    report = analyze_sources({"m.py": src}, rules=["obs-span-leak"])
    assert not report.findings and report.suppressed


# ------------------------------------------ threadpool-discipline rule


def test_threadpool_direct_construction_flagged():
    src = """
from concurrent.futures import ThreadPoolExecutor

def load(paths):
    with ThreadPoolExecutor(max_workers=8) as ex:
        return list(ex.map(len, paths))
"""
    report = analyze_sources({"m.py": src},
                             rules=["threadpool-discipline"])
    assert len(report.findings) == 1
    assert "shared_pool" in report.findings[0].message


def test_threadpool_aliased_imports_flagged():
    src = """
import concurrent.futures as cf
from concurrent import futures

def a():
    return cf.ThreadPoolExecutor(2)

def b():
    return futures.ThreadPoolExecutor(2)
"""
    report = analyze_sources({"m.py": src},
                             rules=["threadpool-discipline"])
    assert len(report.findings) == 2


def test_threadpool_threads_module_exempt():
    src = """
from concurrent.futures import ThreadPoolExecutor

POOL = ThreadPoolExecutor(max_workers=4)
"""
    report = analyze_sources({"delta_tpu/utils/threads.py": src},
                             rules=["threadpool-discipline"])
    assert not report.findings


def test_threadpool_shared_pool_usage_clean():
    src = """
from delta_tpu.utils.threads import parallel_map, shared_pool

def load(paths):
    return parallel_map(len, paths) + shared_pool().map(len, paths)
"""
    report = analyze_sources({"m.py": src},
                             rules=["threadpool-discipline"])
    assert not report.findings


def test_threadpool_suppression_pragma():
    src = """
from concurrent.futures import ThreadPoolExecutor

def oneshot():
    # delta-lint: disable=threadpool-discipline (audited: example)
    with ThreadPoolExecutor(max_workers=1) as ex:
        return ex.submit(int).result()
"""
    report = analyze_sources({"m.py": src},
                             rules=["threadpool-discipline"])
    assert not report.findings and report.suppressed


# ---------------------------------------------- retry-discipline rule


def test_retry_sleep_in_exception_loop_flagged():
    src = """
import time

def fetch(op):
    delay = 0.1
    while True:
        try:
            return op()
        except IOError:
            time.sleep(delay)
            delay *= 2
"""
    report = analyze_sources({"m.py": src}, rules=["retry-discipline"])
    found = _rules_fired(report, "retry-discipline")
    assert found and "RetryPolicy" in found[0].message


def test_retry_sleep_from_import_alias_flagged():
    src = """
from time import sleep as snooze

def fetch(op):
    for _ in range(1000):
        try:
            return op()
        except OSError:
            snooze(0.5)
"""
    report = analyze_sources({"m.py": src}, rules=["retry-discipline"])
    assert _rules_fired(report, "retry-discipline")


def test_retry_literal_attempt_cap_flagged():
    src = """
def fetch(op):
    for attempt in range(3):
        try:
            return op()
        except IOError:
            if attempt == 2:
                raise
"""
    report = analyze_sources({"m.py": src}, rules=["retry-discipline"])
    found = _rules_fired(report, "retry-discipline")
    assert found and "attempt cap" in found[0].message


def test_retry_discipline_negatives_clean():
    # sleep without exception handling (a poller), exception handling
    # without sleep or a literal cap (a scan loop), and a data loop
    # over range with no try — none are retry loops
    src = """
import time

def poll(ready):
    while not ready():
        time.sleep(0.1)

def scan(items, f):
    out = []
    for it in items:
        try:
            out.append(f(it))
        except ValueError:
            pass
    return out

def fill(n):
    return [0 for _ in range(8)]
"""
    report = analyze_sources({"m.py": src}, rules=["retry-discipline"])
    assert not _rules_fired(report, "retry-discipline")


def test_retry_discipline_resilience_package_exempt():
    src = """
import time

def call(fn):
    while True:
        try:
            return fn()
        except IOError:
            time.sleep(0.05)
"""
    report = analyze_sources(
        {"delta_tpu/resilience/policy.py": src},
        rules=["retry-discipline"])
    assert not _rules_fired(report, "retry-discipline")


def test_retry_discipline_suppression_pragma():
    src = """
import time

def fetch(op):
    # delta-lint: disable=retry-discipline (audited: example)
    while True:
        try:
            return op()
        except IOError:
            time.sleep(0.1)
"""
    report = analyze_sources({"m.py": src}, rules=["retry-discipline"])
    assert not report.findings and report.suppressed


def test_retry_silent_device_fallback_flagged():
    # third shape: a device-dispatch try whose handler swallows the
    # error without classifying, counting, or re-raising
    src = """
def read(route, thunk):
    from delta_tpu.resilience import device_faults
    try:
        return device_faults.shed_retry("decode", thunk)
    except Exception:
        return None
"""
    report = analyze_sources({"delta_tpu/x.py": src},
                             rules=["retry-discipline"])
    found = _rules_fired(report, "retry-discipline")
    assert found and "starve the route breaker" in found[0].message


def test_retry_dispatch_handler_each_discipline_clean():
    # classify, count, and re-raise each individually satisfy the
    # contract (incl. dotted/method spellings)
    src = """
def a(thunk, gate):
    from delta_tpu.parallel import gate as g
    try:
        return device_dispatch("k", thunk)
    except Exception as e:
        g.route_failed(gate, e)
        return None

def b(thunk, ctr):
    try:
        return device_dispatch("k", thunk)
    except Exception:
        ctr.inc()
        return None

def c(thunk):
    from delta_tpu.errors import DeltaError
    try:
        return device_dispatch("k", thunk)
    except Exception as e:
        raise DeltaError(str(e)) from e

def d(thunk):
    from delta_tpu.resilience import device_faults
    try:
        return device_faults.shed_retry("skip", thunk)
    except Exception as e:
        if not device_faults.absorb_route_failure("skip", e):
            raise
        return None
"""
    report = analyze_sources({"delta_tpu/x.py": src},
                             rules=["retry-discipline"])
    assert not _rules_fired(report, "retry-discipline")


def test_retry_dispatch_in_nested_scope_not_attributed():
    # a dispatch inside a nested def is its own call site — the outer
    # try that merely BUILDS the closure is not a dispatch site
    src = """
def plan(thunk):
    try:
        def later():
            return device_dispatch("k", thunk)
        return later
    except Exception:
        return None
"""
    report = analyze_sources({"delta_tpu/x.py": src},
                             rules=["retry-discipline"])
    assert not _rules_fired(report, "retry-discipline")


def test_retry_silent_fallback_resilience_path_exempt():
    src = """
def absorb(thunk):
    try:
        return device_dispatch("k", thunk)
    except Exception:
        return None
"""
    report = analyze_sources(
        {"delta_tpu/resilience/device_faults.py": src},
        rules=["retry-discipline"])
    assert not _rules_fired(report, "retry-discipline")


# ------------------------------------------------- handler-discipline


def test_handler_discipline_raw_thread_flagged():
    src = """
import threading

def handle(conn):
    t = threading.Thread(target=lambda: None, daemon=True)
    t.start()
"""
    report = analyze_sources({"delta_tpu/serve/handlers.py": src},
                             rules=["handler-discipline"])
    fired = _rules_fired(report, "handler-discipline")
    assert len(fired) == 1 and "pool.spawn" in fired[0].message


def test_handler_discipline_from_import_thread_flagged():
    src = """
from threading import Thread as T

def accept_loop(listener):
    while True:
        T(target=listener.accept).start()
"""
    report = analyze_sources({"delta_tpu/serve/server2.py": src},
                             rules=["handler-discipline"])
    assert _rules_fired(report, "handler-discipline")


def test_handler_discipline_pool_module_exempt():
    src = """
import threading

def spawn(name, target):
    t = threading.Thread(target=target, daemon=True)
    t.start()
    return t
"""
    report = analyze_sources({"delta_tpu/serve/pool.py": src},
                             rules=["handler-discipline"])
    assert not _rules_fired(report, "handler-discipline")


def test_handler_discipline_outside_serve_exempt():
    """The rule is scoped: the same shapes elsewhere in the tree are the
    business of threadpool-discipline / resilience defaults."""
    src = """
import threading
from delta_tpu.resilience import io_call

def elsewhere(store):
    threading.Thread(target=lambda: None).start()
    return io_call("file", lambda: store.read("p"))
"""
    report = analyze_sources({"delta_tpu/storage/other.py": src},
                             rules=["handler-discipline"])
    assert not _rules_fired(report, "handler-discipline")


def test_handler_discipline_naked_io_call_flagged():
    src = """
from delta_tpu.resilience import io_call

def refresh(store):
    return io_call("file", lambda: store.list_from("p"))
"""
    report = analyze_sources({"delta_tpu/serve/cachey.py": src},
                             rules=["handler-discipline"])
    fired = _rules_fired(report, "handler-discipline")
    assert len(fired) == 1 and "deadline" in fired[0].message


def test_handler_discipline_scoped_io_call_ok():
    src = """
from delta_tpu.resilience import deadline_scope, io_call

def refresh(store, budget_s):
    with deadline_scope(budget_s):
        return io_call("file", lambda: store.list_from("p"))
"""
    report = analyze_sources({"delta_tpu/serve/cachey.py": src},
                             rules=["handler-discipline"])
    assert not _rules_fired(report, "handler-discipline")


def test_handler_discipline_module_alias_io_call_flagged():
    src = """
from delta_tpu import resilience

def refresh(store):
    return resilience.io_call("file", lambda: store.read("p"))
"""
    report = analyze_sources({"delta_tpu/serve/cachey.py": src},
                             rules=["handler-discipline"])
    assert _rules_fired(report, "handler-discipline")


def test_handler_discipline_suppression_pragma():
    src = """
import threading

def special(target):
    # delta-lint: disable=handler-discipline (audited: example)
    return threading.Thread(target=target)
"""
    report = analyze_sources({"delta_tpu/serve/x.py": src},
                             rules=["handler-discipline"])
    assert not report.findings and report.suppressed


# ----------------------------------------------- shared-state-race


RACE = ["shared-state-race"]


def test_race_rmw_from_two_thread_roots_flagged():
    src = """
import threading

class Stats:
    def __init__(self):
        self.n = 0

    def bump(self):
        self.n += 1

STATS = Stats()

def worker_a():
    STATS.bump()

def worker_b():
    STATS.bump()

def main():
    threading.Thread(target=worker_a).start()
    threading.Thread(target=worker_b).start()
"""
    report = analyze_sources({"m.py": src}, rules=RACE)
    fired = _rules_fired(report, "shared-state-race")
    assert fired and "Stats.n" in fired[0].message
    assert "thread-root sites" in fired[0].message


def test_race_owning_lock_held_two_call_levels_silent():
    """Held-locks context must propagate interprocedurally: the lock is
    taken two call frames above the mutation."""
    src = """
import threading

class Stats:
    def __init__(self):
        self._lock = threading.Lock()
        self.n = 0

    def bump(self):
        with self._lock:
            self._bump_locked()

    def _bump_locked(self):
        self._inc()

    def _inc(self):
        self.n += 1

STATS = Stats()

def worker_a():
    STATS.bump()

def worker_b():
    STATS.bump()

def main():
    threading.Thread(target=worker_a).start()
    threading.Thread(target=worker_b).start()
"""
    report = analyze_sources({"m.py": src}, rules=RACE)
    assert not report.findings


def test_race_one_unlocked_path_still_flagged():
    """Meet-over-paths: a lock held on only ONE of two paths from a
    thread root does not protect the mutation."""
    src = """
import threading

class Stats:
    def __init__(self):
        self._lock = threading.Lock()
        self.n = 0

    def bump(self):
        with self._lock:
            self._inc()

    def bump_unsafe(self):
        self._inc()

    def _inc(self):
        self.n += 1

STATS = Stats()

def worker_a():
    STATS.bump()

def worker_b():
    STATS.bump_unsafe()

def main():
    threading.Thread(target=worker_a).start()
    threading.Thread(target=worker_b).start()
"""
    report = analyze_sources({"m.py": src}, rules=RACE)
    assert _rules_fired(report, "shared-state-race")


def test_race_partial_thread_target_resolved():
    src = """
import functools
import threading

class Stats:
    def __init__(self):
        self.n = 0

    def bump(self, k):
        self.n += k

STATS = Stats()

def hit(k=1):
    STATS.bump(k)

def main():
    threading.Thread(target=functools.partial(hit, 2)).start()
    threading.Thread(target=hit).start()
"""
    report = analyze_sources({"m.py": src}, rules=RACE)
    assert _rules_fired(report, "shared-state-race")


def test_race_dict_dispatch_reachability():
    src = """
import threading

LOG = []

def do_a():
    LOG.append("a")

def do_b():
    LOG.append("b")

HANDLERS = {"a": do_a, "b": do_b}

def dispatch(key):
    HANDLERS[key]()

def serve():
    while True:
        threading.Thread(target=dispatch).start()
"""
    report = analyze_sources({"m.py": src}, rules=RACE)
    fired = _rules_fired(report, "shared-state-race")
    assert len(fired) == 2  # both dispatch values reached
    assert all("LOG" in f.message for f in fired)


def test_race_executor_submit_is_multi_root():
    """A single submit-in-a-loop site implies concurrency on its own:
    no second root needed."""
    src = """
from concurrent.futures import ThreadPoolExecutor

class Stats:
    def __init__(self):
        self.n = 0

    def bump(self):
        self.n += 1

STATS = Stats()

def worker():
    STATS.bump()

def main(items):
    ex = ThreadPoolExecutor(4)
    for _ in items:
        ex.submit(worker)
"""
    report = analyze_sources({"m.py": src}, rules=RACE)
    assert _rules_fired(report, "shared-state-race")


def test_race_obs_wrap_is_thread_root():
    src = """
from delta_tpu import obs

class Stats:
    def __init__(self):
        self.n = 0

    def bump(self):
        self.n += 1

STATS = Stats()

def worker():
    STATS.bump()

def main():
    return obs.wrap(worker)
"""
    report = analyze_sources({"m.py": src}, rules=RACE)
    assert _rules_fired(report, "shared-state-race")


def test_race_plain_store_exempt():
    """Attribute rebinding is atomic publication under the GIL — the
    idiomatic lock-free hand-off stays silent."""
    src = """
import threading

class Holder:
    def __init__(self):
        self.latest = None

    def publish(self, x):
        self.latest = x

H = Holder()

def worker_a():
    H.publish(1)

def worker_b():
    H.publish(2)

def main():
    threading.Thread(target=worker_a).start()
    threading.Thread(target=worker_b).start()
"""
    report = analyze_sources({"m.py": src}, rules=RACE)
    assert not report.findings


def test_race_threadsafe_attr_type_exempt():
    src = """
import queue
import threading

class Mailbox:
    def __init__(self):
        self.q = queue.Queue()

    def deliver(self, x):
        self.q.update(x)

M = Mailbox()

def worker_a():
    M.deliver(1)

def worker_b():
    M.deliver(2)

def main():
    threading.Thread(target=worker_a).start()
    threading.Thread(target=worker_b).start()
"""
    report = analyze_sources({"m.py": src}, rules=RACE)
    assert not report.findings


def test_race_init_mutations_exempt():
    src = """
import threading

class Cache:
    def __init__(self):
        self.store = {}
        self.store["warm"] = True

def worker_a():
    Cache()

def worker_b():
    Cache()

def main():
    threading.Thread(target=worker_a).start()
    threading.Thread(target=worker_b).start()
"""
    report = analyze_sources({"m.py": src}, rules=RACE)
    assert not report.findings


# ------------------------------------------------- transfer budget


def _write_budget(tmp_path, monkeypatch, paths, modules=(), audited=()):
    doc = {"modules": list(modules),
           "audited_transfer_sites": list(audited), "paths": paths}
    p = tmp_path / "budget.json"
    p.write_text(json.dumps(doc))
    monkeypatch.setenv("DELTA_LINT_TRANSFER_BUDGET", str(p))


_SHIP_ENTRY = {
    "site": "pkg/ship.py::ship",
    "unit": "slot",
    "budget_bytes_per_unit": 8,
    "device_put_exhaustive": True,
    "lanes": [
        {"name": "idx", "kind": "dtype", "dtype": "int32"},
        {"name": "val", "kind": "dtype", "dtype": "uint32"},
    ],
}

_SHIP_SRC = """
import numpy as np
import jax

def ship(n):
    idx = np.full((4, n), 0, np.int32)
    val = np.zeros((4, n), np.uint32)
    jax.device_put(idx)
    jax.device_put(val)
    return idx, val
"""


def test_budget_in_budget_site_clean(tmp_path, monkeypatch):
    _write_budget(tmp_path, monkeypatch, {"ship": _SHIP_ENTRY})
    report = analyze_sources({"pkg/ship.py": _SHIP_SRC},
                             rules=["transfer-budget"])
    assert not report.findings


def test_budget_widened_dtype_flagged_with_diff(tmp_path, monkeypatch):
    _write_budget(tmp_path, monkeypatch, {"ship": _SHIP_ENTRY})
    src = _SHIP_SRC.replace("np.int32", "np.int64")
    report = analyze_sources({"pkg/ship.py": src},
                             rules=["transfer-budget"])
    fired = _rules_fired(report, "transfer-budget")
    assert fired and "widened" in fired[0].message
    assert "int64" in fired[0].message and "int32" in fired[0].message
    assert "8 B/unit" in fired[0].message \
        and "4 B/unit" in fired[0].message


def test_budget_extra_device_put_lane_flagged(tmp_path, monkeypatch):
    _write_budget(tmp_path, monkeypatch, {"ship": _SHIP_ENTRY})
    src = _SHIP_SRC.replace(
        "    return idx, val",
        "    extra = np.zeros(n, np.uint8)\n"
        "    jax.device_put(extra)\n"
        "    return idx, val")
    report = analyze_sources({"pkg/ship.py": src},
                             rules=["transfer-budget"])
    fired = _rules_fired(report, "transfer-budget")
    assert fired and "not a budgeted lane" in fired[0].message


def test_budget_bitplane_lane_clean(tmp_path, monkeypatch):
    entry = {
        "site": "pkg/plane.py::route",
        "budget_bytes_per_unit": 0.25,
        "lanes": [{"name": "flag_words", "kind": "bitplane"},
                  {"name": "add_words", "kind": "bitplane"}],
    }
    src = """
import numpy as np

def route(flags, adds):
    flag_words = np.packbits(flags, axis=1,
                             bitorder="little").view(np.uint32)
    add_words = np.packbits(adds, axis=1,
                            bitorder="little").view(np.uint32)
    return flag_words, add_words
"""
    _write_budget(tmp_path, monkeypatch, {"plane": entry})
    report = analyze_sources({"pkg/plane.py": src},
                             rules=["transfer-budget"])
    assert not report.findings


def test_budget_unpacked_bitplane_flagged(tmp_path, monkeypatch):
    entry = {
        "site": "pkg/plane.py::route",
        "budget_bytes_per_unit": 0.125,
        "lanes": [{"name": "flag_words", "kind": "bitplane"}],
    }
    src = """
import numpy as np

def route(flags):
    flag_words = np.asarray(flags, np.uint32)
    return flag_words
"""
    _write_budget(tmp_path, monkeypatch, {"plane": entry})
    report = analyze_sources({"pkg/plane.py": src},
                             rules=["transfer-budget"])
    fired = _rules_fired(report, "transfer-budget")
    assert fired and "no longer a packed bitplane" in fired[0].message


def test_budget_missing_lane_flagged(tmp_path, monkeypatch):
    _write_budget(tmp_path, monkeypatch, {"ship": _SHIP_ENTRY})
    src = _SHIP_SRC.replace("idx", "indices")
    report = analyze_sources({"pkg/ship.py": src},
                             rules=["transfer-budget"])
    fired = _rules_fired(report, "transfer-budget")
    assert fired and "not assigned" in fired[0].message


def test_budget_stale_site_flagged(tmp_path, monkeypatch):
    _write_budget(tmp_path, monkeypatch, {"ship": _SHIP_ENTRY})
    src = _SHIP_SRC.replace("def ship", "def ship_v2")
    report = analyze_sources({"pkg/ship.py": src},
                             rules=["transfer-budget"])
    fired = _rules_fired(report, "transfer-budget")
    assert fired and "not found" in fired[0].message


def test_budget_sum_mismatch_flagged(tmp_path, monkeypatch):
    entry = dict(_SHIP_ENTRY, budget_bytes_per_unit=4)
    _write_budget(tmp_path, monkeypatch, {"ship": entry})
    report = analyze_sources({"pkg/ship.py": _SHIP_SRC},
                             rules=["transfer-budget"])
    fired = _rules_fired(report, "transfer-budget")
    assert fired and "!= manifest budget" in fired[0].message


def test_budget_scalar_lane_excluded_from_sum(tmp_path, monkeypatch):
    entry = dict(_SHIP_ENTRY)
    entry = json.loads(json.dumps(entry))  # deep copy
    entry["lanes"].append(
        {"name": "n_op", "kind": "scalar", "dtype": "int32"})
    src = _SHIP_SRC.replace(
        "    return idx, val",
        "    n_op = np.asarray(n, np.int32)\n"
        "    jax.device_put(n_op)\n"
        "    return idx, val")
    _write_budget(tmp_path, monkeypatch, {"ship": entry})
    report = analyze_sources({"pkg/ship.py": src},
                             rules=["transfer-budget"])
    assert not report.findings


def test_unbudgeted_device_put_flagged_and_audit_exempt(
        tmp_path, monkeypatch):
    src = """
import jax
import numpy as np

def rogue(x):
    return jax.device_put(np.asarray(x, np.int64))

def audited(x):
    return jax.device_put(x)
"""
    _write_budget(tmp_path, monkeypatch, {},
                  modules=["pkg/xfer.py"],
                  audited=["pkg/xfer.py::audited"])
    report = analyze_sources({"pkg/xfer.py": src},
                             rules=["transfer-unbudgeted"])
    fired = _rules_fired(report, "transfer-unbudgeted")
    assert len(fired) == 1 and "rogue" in fired[0].message


def test_unbudgeted_ignores_modules_off_manifest(tmp_path, monkeypatch):
    src = """
import jax

def free(x):
    return jax.device_put(x)
"""
    _write_budget(tmp_path, monkeypatch, {}, modules=["pkg/xfer.py"])
    report = analyze_sources({"pkg/elsewhere.py": src},
                             rules=["transfer-unbudgeted"])
    assert not report.findings


# shaped like ops/json_parse.py::parse_window_fields: one padded uint8
# window lane, budgeted at 1 B/unit
_WINDOW_ENTRY = {
    "site": "pkg/jparse.py::parse_window",
    "unit": "padded window byte",
    "budget_bytes_per_unit": 1,
    "device_put_exhaustive": True,
    "lanes": [{"name": "lane_bytes", "kind": "dtype", "dtype": "uint8"}],
}

_WINDOW_SRC = """
import numpy as np
import jax

def parse_window(window, n):
    lane_bytes = np.full(n + 32, 0x20, np.uint8)
    jax.device_put(lane_bytes)
    return lane_bytes
"""


def test_budget_byte_window_lane_clean(tmp_path, monkeypatch):
    _write_budget(tmp_path, monkeypatch, {"jparse": _WINDOW_ENTRY})
    report = analyze_sources({"pkg/jparse.py": _WINDOW_SRC},
                             rules=["transfer-budget"])
    assert not report.findings


def test_budget_byte_window_widened_flagged(tmp_path, monkeypatch):
    # the r17 failure mode: a uint8 window lane silently widening to
    # int32 quadruples the parse plane's H2D bytes
    _write_budget(tmp_path, monkeypatch, {"jparse": _WINDOW_ENTRY})
    src = _WINDOW_SRC.replace("np.uint8", "np.int32")
    report = analyze_sources({"pkg/jparse.py": src},
                             rules=["transfer-budget"])
    fired = _rules_fired(report, "transfer-budget")
    assert fired and "widened" in fired[0].message


# shaped like ops/stats.py::decode_mask_words: mixed-dtype decode lanes
# (int64 bit index + uint32 bitmap words + int32 word positions)
_DECODE_ENTRY = {
    "site": "pkg/dvdec.py::decode_words",
    "unit": "padded decode element",
    "budget_bytes_per_unit": 16,
    "device_put_exhaustive": True,
    "lanes": [
        {"name": "lane_bit_idx", "kind": "dtype", "dtype": "int64"},
        {"name": "lane_bm_words", "kind": "dtype", "dtype": "uint32"},
        {"name": "lane_bm_pos", "kind": "dtype", "dtype": "int32"},
    ],
}

_DECODE_SRC = """
import numpy as np
import jax

def decode_words(bit_idx, bm_words, bm_pos, n_words):
    lane_bit_idx = np.full(8, n_words * 32, np.int64)
    lane_bm_words = np.zeros(8, np.uint32)
    lane_bm_pos = np.full(8, n_words, np.int32)
    jax.device_put(lane_bit_idx)
    jax.device_put(lane_bm_words)
    jax.device_put(lane_bm_pos)
    return lane_bit_idx, lane_bm_words, lane_bm_pos
"""


def test_budget_decode_lanes_clean(tmp_path, monkeypatch):
    _write_budget(tmp_path, monkeypatch, {"dvdec": _DECODE_ENTRY})
    report = analyze_sources({"pkg/dvdec.py": _DECODE_SRC},
                             rules=["transfer-budget"])
    assert not report.findings


def test_budget_decode_extra_lane_flagged(tmp_path, monkeypatch):
    _write_budget(tmp_path, monkeypatch, {"dvdec": _DECODE_ENTRY})
    src = _DECODE_SRC.replace(
        "    return lane_bit_idx, lane_bm_words, lane_bm_pos",
        "    lane_runs = np.zeros(8, np.int64)\n"
        "    jax.device_put(lane_runs)\n"
        "    return lane_bit_idx, lane_bm_words, lane_bm_pos")
    report = analyze_sources({"pkg/dvdec.py": src},
                             rules=["transfer-budget"])
    fired = _rules_fired(report, "transfer-budget")
    assert fired and "not a budgeted lane" in fired[0].message


# -------------------------------------------------- scan cache / changed


def test_scan_cache_hit_reproduces_report(tmp_path):
    from delta_tpu.tools.analyzer.cache import analyze_paths_cached

    target = tmp_path / "pkg"
    target.mkdir()
    (target / "a.py").write_text("def f(x=[]):\n    return x\n")
    cache = tmp_path / "cache.json"
    r1, s1 = analyze_paths_cached([str(target)],
                                  cache_path=str(cache))
    assert s1["cache"] == "cold"
    r2, s2 = analyze_paths_cached([str(target)],
                                  cache_path=str(cache))
    assert s2["cache"] == "hit" and s2["changed_files"] == 0
    assert [f.message for f in r2.findings] \
        == [f.message for f in r1.findings]
    assert r2.rules_run == r1.rules_run
    assert r2.files_scanned == r1.files_scanned


def test_scan_cache_invalidated_by_content_change(tmp_path):
    from delta_tpu.tools.analyzer.cache import analyze_paths_cached

    target = tmp_path / "pkg"
    target.mkdir()
    mod = target / "a.py"
    mod.write_text("def f():\n    return 1\n")
    cache = tmp_path / "cache.json"
    r1, _ = analyze_paths_cached([str(target)], cache_path=str(cache))
    assert not r1.findings
    mod.write_text("def f(x=[]):\n    return x\n")
    r2, s2 = analyze_paths_cached([str(target)], cache_path=str(cache))
    assert s2["cache"] == "stale" and s2["changed_files"] == 1
    assert _rules_fired(r2, "mutable-default")


def test_scan_cache_touch_without_change_still_hits(tmp_path):
    from delta_tpu.tools.analyzer.cache import analyze_paths_cached

    target = tmp_path / "pkg"
    target.mkdir()
    mod = target / "a.py"
    mod.write_text("def f():\n    return 1\n")
    cache = tmp_path / "cache.json"
    analyze_paths_cached([str(target)], cache_path=str(cache))
    os.utime(mod)  # mtime moves, bytes identical
    _, stats = analyze_paths_cached([str(target)],
                                    cache_path=str(cache))
    assert stats["cache"] == "hit"


def test_cli_changed_mode_exit_codes(tmp_path, capsys):
    bad = tmp_path / "bad.py"
    bad.write_text("def f(x=[]):\n    return x\n")
    cache = tmp_path / "cache.json"
    argv = [str(bad), "--changed", "--cache-file", str(cache)]
    assert lint_main(argv) == 1
    capsys.readouterr()
    assert lint_main(argv) == 1  # cache hit must not mask findings
    bad.write_text("def f(x=None):\n    return x\n")
    capsys.readouterr()
    assert lint_main(argv) == 0


# ------------------------------------------------------------ baseline


def test_baseline_write_then_check_passes(tmp_path, capsys,
                                          monkeypatch):
    monkeypatch.chdir(tmp_path)
    bad = tmp_path / "bad.py"
    bad.write_text("def f(x=[]):\n    return x\n")
    bl = tmp_path / "bl.json"
    assert lint_main([str(bad), "--baseline", "write",
                      "--baseline-file", str(bl)]) == 0
    capsys.readouterr()
    assert lint_main([str(bad), "--baseline", "check",
                      "--baseline-file", str(bl)]) == 0
    out = capsys.readouterr().out
    assert "1 baselined" in out


def test_baseline_new_finding_fails(tmp_path, capsys, monkeypatch):
    monkeypatch.chdir(tmp_path)
    bad = tmp_path / "bad.py"
    bad.write_text("def f(x=[]):\n    return x\n")
    bl = tmp_path / "bl.json"
    lint_main([str(bad), "--baseline", "write",
               "--baseline-file", str(bl)])
    bad.write_text("def f(x=[]):\n    return x\n"
                   "def g(y={}):\n    return y\n")
    capsys.readouterr()
    assert lint_main([str(bad), "--baseline", "check",
                      "--baseline-file", str(bl)]) == 1
    out = capsys.readouterr().out
    assert "g()" in out and "1 finding(s)" in out


def test_baseline_fingerprint_survives_line_shift(tmp_path, capsys,
                                                  monkeypatch):
    monkeypatch.chdir(tmp_path)
    bad = tmp_path / "bad.py"
    bad.write_text("def f(x=[]):\n    return x\n")
    bl = tmp_path / "bl.json"
    lint_main([str(bad), "--baseline", "write",
               "--baseline-file", str(bl)])
    bad.write_text("# pushed down two lines\n# by these comments\n"
                   "def f(x=[]):\n    return x\n")
    capsys.readouterr()
    assert lint_main([str(bad), "--baseline", "check",
                      "--baseline-file", str(bl)]) == 0


def test_baseline_check_without_file_is_usage_error(tmp_path, capsys):
    bad = tmp_path / "bad.py"
    bad.write_text("def f(x=[]):\n    return x\n")
    assert lint_main([str(bad), "--baseline", "check",
                      "--baseline-file",
                      str(tmp_path / "missing.json")]) == 2


# -------------------------------------------------------- SARIF upgrade


def test_sarif_rules_carry_help_uris():
    report = analyze_sources({"m.py": "def f(x=[]):\n    return x\n"})
    doc = json.loads(render_json(report))
    rules = doc["runs"][0]["tool"]["driver"]["rules"]
    by_id = {r["id"]: r for r in rules}
    assert by_id["shared-state-race"]["helpUri"] \
        == "docs/static_analysis.md#shared-state-race"
    assert by_id["transfer-budget"]["helpUri"] \
        == "docs/static_analysis.md#transfer-budget"
    assert by_id["transfer-unbudgeted"]["helpUri"] \
        == "docs/static_analysis.md#transfer-budget"
    assert all("helpUri" in r for r in rules)


def test_sarif_suppressed_results_carry_suppression_records():
    src = ("def f(x=[]):  # delta-lint: disable=mutable-default ok\n"
           "    return x\n")
    report = analyze_sources({"m.py": src})
    doc = json.loads(render_json(report))
    sup = doc["runs"][0]["suppressedResults"]
    assert sup and sup[0]["suppressions"][0]["kind"] == "inSource"


def test_sarif_baseline_states(tmp_path):
    from delta_tpu.tools.analyzer.baseline import (
        apply_baseline,
        load_baseline,
        write_baseline,
    )

    bad = tmp_path / "bad.py"
    bad.write_text("def f(x=[]):\n    return x\n")
    report = analyze_paths([str(bad)])
    bl = tmp_path / "bl.json"
    write_baseline(str(bl), report)
    bad.write_text("def f(x=[]):\n    return x\n"
                   "def g(y={}):\n    return y\n")
    checked = apply_baseline(analyze_paths([str(bad)]),
                             load_baseline(str(bl)))
    doc = json.loads(render_json(checked))
    run = doc["runs"][0]
    assert [r["baselineState"] for r in run["results"]] == ["new"]
    assert [r["baselineState"] for r in run["baselinedResults"]] \
        == ["unchanged"]


# ------------------------------------------------- unprofiled dispatch


_DISPATCH_ENV = "DELTA_LINT_DISPATCH_MODULES"

_FUNNELED_SRC = """
import jax
from delta_tpu import obs

def launch(arr):
    with obs.device_dispatch("k.launch", key=(arr.shape[0],)) as dd:
        dd.h2d("arr", arr)
        return jax.device_put(arr)
"""

_BARE_SRC = """
import jax

def launch(arr):
    return jax.device_put(arr)
"""


def test_dispatch_funneled_clean(monkeypatch):
    monkeypatch.setenv(_DISPATCH_ENV, "pkg/k.py")
    report = analyze_sources({"pkg/k.py": _FUNNELED_SRC},
                             rules=["unprofiled-dispatch"])
    assert not report.findings


def test_dispatch_bare_device_put_flagged(monkeypatch):
    monkeypatch.setenv(_DISPATCH_ENV, "pkg/k.py")
    report = analyze_sources({"pkg/k.py": _BARE_SRC},
                             rules=["unprofiled-dispatch"])
    fired = _rules_fired(report, "unprofiled-dispatch")
    assert fired and "launch()" in fired[0].message


def test_dispatch_uncovered_module_ignored(monkeypatch):
    monkeypatch.setenv(_DISPATCH_ENV, "pkg/other.py")
    report = analyze_sources({"pkg/k.py": _BARE_SRC},
                             rules=["unprofiled-dispatch"])
    assert not report.findings


def test_dispatch_allowlisted_helper_clean(monkeypatch):
    monkeypatch.setenv(_DISPATCH_ENV, "pkg/k.py")
    monkeypatch.setenv("DELTA_LINT_DISPATCH_ALLOW", "launch")
    report = analyze_sources({"pkg/k.py": _BARE_SRC},
                             rules=["unprofiled-dispatch"])
    assert not report.findings


def test_dispatch_multi_item_with_covers(monkeypatch):
    """`with device_dispatch(...) as dd, other():` still counts, and so
    does a device_put nested deeper inside the block."""
    src = """
import jax
import contextlib
from delta_tpu import obs

def launch(arr, flag):
    with obs.device_dispatch("k.launch") as dd, contextlib.nullcontext():
        if flag:
            for _ in range(2):
                jax.device_put(arr)
    return arr
"""
    monkeypatch.setenv(_DISPATCH_ENV, "pkg/k.py")
    report = analyze_sources({"pkg/k.py": src},
                             rules=["unprofiled-dispatch"])
    assert not report.findings


# -------------------------------------- resident-ledger-discipline


_LEDGER_ENV = "DELTA_LINT_LEDGER_MODULES"

_LEDGER_CLEAN_SRC = """
import jax
from delta_tpu.obs import hbm

class Lane:
    def __init__(self, arr):
        dev = jax.device_put(arr)
        self._hbm = hbm.register(self, kind="replay-keys", arrays=(dev,))

    def release(self):
        self._hbm.release()
"""


def test_ledger_registered_and_released_clean(monkeypatch):
    monkeypatch.setenv(_LEDGER_ENV, "pkg/owner.py")
    report = analyze_sources({"pkg/owner.py": _LEDGER_CLEAN_SRC},
                             rules=["resident-ledger-discipline"])
    assert not report.findings


def test_ledger_register_without_release_flagged(monkeypatch):
    src = """
from delta_tpu.obs import hbm

class Lane:
    def __init__(self, arr):
        self._hbm = hbm.register(self, kind="replay-keys", arrays=(arr,))
"""
    monkeypatch.setenv(_LEDGER_ENV, "pkg/owner.py")
    report = analyze_sources({"pkg/owner.py": src},
                             rules=["resident-ledger-discipline"])
    fired = _rules_fired(report, "resident-ledger-discipline")
    assert len(fired) == 1 and "'_hbm'" in fired[0].message \
        and "release" in fired[0].message


def test_ledger_discarded_register_flagged(monkeypatch):
    src = """
from delta_tpu.obs import hbm

def make(arr):
    hbm.register(None, kind="stats-index", arrays=(arr,))
"""
    monkeypatch.setenv(_LEDGER_ENV, "pkg/owner.py")
    report = analyze_sources({"pkg/owner.py": src},
                             rules=["resident-ledger-discipline"])
    fired = _rules_fired(report, "resident-ledger-discipline")
    assert len(fired) == 1 and "discarded" in fired[0].message


def test_ledger_unregistered_lane_class_flagged(monkeypatch):
    src = """
import jax

class Lane:
    def upload(self, arr):
        self.dev = jax.device_put(arr)
"""
    monkeypatch.setenv(_LEDGER_ENV, "pkg/owner.py")
    report = analyze_sources({"pkg/owner.py": src},
                             rules=["resident-ledger-discipline"])
    fired = _rules_fired(report, "resident-ledger-discipline")
    assert len(fired) == 1 and "Lane" in fired[0].message \
        and "hbm.register" in fired[0].message


def test_ledger_uncovered_module_ignored(monkeypatch):
    src = """
import jax

class Lane:
    def upload(self, arr):
        self.dev = jax.device_put(arr)
"""
    monkeypatch.setenv(_LEDGER_ENV, "pkg/other.py")
    report = analyze_sources({"pkg/owner.py": src},
                             rules=["resident-ledger-discipline"])
    assert not report.findings


def test_ledger_name_bound_release_clean(monkeypatch):
    """A handle bound to a local name counts when `.release()` is
    called on that name (the transient handoff-lane shape)."""
    src = """
from delta_tpu.obs import hbm

def decode(arr):
    h = hbm.register(None, kind="ckpt-handoff", arrays=(arr,))
    try:
        return arr
    finally:
        h.release()
"""
    monkeypatch.setenv(_LEDGER_ENV, "pkg/owner.py")
    report = analyze_sources({"pkg/owner.py": src},
                             rules=["resident-ledger-discipline"])
    assert not report.findings


def test_ledger_real_owner_modules_clean():
    """The shipped resident owners (replay key lanes, stats-index
    lanes, checkpoint handoff) must satisfy the discipline rule —
    whole-repo zero findings is an acceptance gate for this pass."""
    import delta_tpu

    pkg = os.path.dirname(delta_tpu.__file__)
    sources = {}
    for rel in ("parallel/resident.py", "stats/device_index.py",
                "ops/page_decode.py"):
        with open(os.path.join(pkg, rel), encoding="utf-8") as f:
            sources[f"delta_tpu/{rel}"] = f.read()
    report = analyze_sources(sources, rules=["resident-ledger-discipline"])
    assert not report.findings


# -------------------------------------------------------- route-contract


_GATE_SRC = """
import os
from delta_tpu.obs.device import record_gate_decision

ROUTES = {{
    "demo": RouteSpec(env="DELTA_TPU_DEMO",
                      fallback_counter="demo.fallbacks",
                      doc_anchor="demo-route"),{extra_route}
}}

def _decide(gate, chosen):
    record_gate_decision(gate, chosen, {{}}, None, "x")
    return chosen

def demo_route(n):{env_read}
    if n > 100:
        return _decide("demo", "device")
    return _decide("demo", "host")
"""

_OBS_SRC = "CAPTURE_ENV_KEYS = ({keys})\n"

_WORKER_SRC = """
from delta_tpu import obs

_FB = obs.counter("demo.fallbacks")

def run(x):
    with obs.device_dispatch("demo.launch", gate="demo",
                             budget={budget!r}):
        pass
{extra_dispatch}
def fell_back(err):
    {inc}
    {observe}
"""


def _route_fixture(tmp_path, monkeypatch, *, env_read=True,
                   capture_key=True, budget="demo-lane",
                   extra_route="", extra_dispatch="", inc=True,
                   observe=True, counter_cataloged=True,
                   doc_heading="## Demo route", gate_src=None):
    """Assemble the conformant three-module route fixture, optionally
    mutated, and run the route-contract pass over it."""
    manifest = tmp_path / "budget.json"
    manifest.write_text(json.dumps({
        "modules": [], "audited_transfer_sites": [],
        "paths": {"demo-lane": {"site": "pkg/worker.py::run"}},
    }))
    catalog = tmp_path / "metrics.json"
    catalog.write_text(json.dumps({
        "counters": ({"demo.fallbacks": "route fell back"}
                     if counter_cataloged else {}),
        "histograms": {}, "gauges": {},
    }))
    doc = tmp_path / "architecture.md"
    doc.write_text(f"# Design\n\n{doc_heading}\n\nprose\n")
    monkeypatch.setenv("DELTA_LINT_GATE_MODULE", "pkg/gate.py")
    monkeypatch.setenv("DELTA_LINT_OBS_MODULE", "pkg/obsmod.py")
    monkeypatch.setenv("DELTA_LINT_ARCH_DOC", str(doc))
    monkeypatch.setenv("DELTA_LINT_TRANSFER_BUDGET", str(manifest))
    monkeypatch.setenv("DELTA_LINT_METRIC_CATALOG", str(catalog))
    sources = {
        "pkg/gate.py": gate_src if gate_src is not None
        else _GATE_SRC.format(
            extra_route=extra_route,
            env_read=('\n    env = os.environ.get("DELTA_TPU_DEMO")'
                      if env_read else "")),
        "pkg/obsmod.py": _OBS_SRC.format(
            keys='"DELTA_TPU_DEMO",' if capture_key else ""),
        "pkg/worker.py": _WORKER_SRC.format(
            budget=budget, extra_dispatch=extra_dispatch,
            inc="_FB.inc()" if inc else "pass",
            observe=('obs.gate_observation("demo", 1.0)'
                     if observe else "pass")),
    }
    return analyze_sources(sources, rules=["route-contract"])


def test_route_contract_conformant_route_is_clean(tmp_path, monkeypatch):
    report = _route_fixture(tmp_path, monkeypatch)
    assert not report.findings, [f.message for f in report.findings]


def test_route_contract_missing_env_read(tmp_path, monkeypatch):
    report = _route_fixture(tmp_path, monkeypatch, env_read=False)
    found = _rules_fired(report, "route-contract")
    assert len(found) == 1
    assert "is never read in demo_route()" in found[0].message


def test_route_contract_missing_capture_stamp(tmp_path, monkeypatch):
    report = _route_fixture(tmp_path, monkeypatch, capture_key=False)
    found = _rules_fired(report, "route-contract")
    assert len(found) == 1
    assert "not in CAPTURE_ENV_KEYS" in found[0].message


def test_route_contract_unknown_budget_name(tmp_path, monkeypatch):
    report = _route_fixture(tmp_path, monkeypatch, budget="no-such-lane")
    found = _rules_fired(report, "route-contract")
    assert len(found) == 1
    assert "has no transfer_budget.json path entry" in found[0].message
    assert found[0].path == "pkg/worker.py"


def test_route_contract_unaudited_dispatch_site(tmp_path, monkeypatch):
    extra = """
def rogue(x):
    with obs.device_dispatch("demo.rogue", gate="demo"):
        pass
"""
    report = _route_fixture(tmp_path, monkeypatch, extra_dispatch=extra)
    found = _rules_fired(report, "route-contract")
    assert len(found) == 1
    assert "not an audited transfer site" in found[0].message
    assert "rogue" in found[0].message


def test_route_contract_missing_gate_observation(tmp_path, monkeypatch):
    report = _route_fixture(tmp_path, monkeypatch, observe=False)
    found = _rules_fired(report, "route-contract")
    assert len(found) == 1
    assert "no gate_observation" in found[0].message


def test_route_contract_fallback_counter_never_incremented(
        tmp_path, monkeypatch):
    report = _route_fixture(tmp_path, monkeypatch, inc=False)
    found = _rules_fired(report, "route-contract")
    assert len(found) == 1
    assert "never created-and-incremented" in found[0].message


def test_route_contract_fallback_counter_uncataloged(
        tmp_path, monkeypatch):
    report = _route_fixture(tmp_path, monkeypatch,
                            counter_cataloged=False)
    found = _rules_fired(report, "route-contract")
    assert len(found) == 1
    assert "not cataloged in metric_names.json" in found[0].message


def test_route_contract_doc_anchor_missing(tmp_path, monkeypatch):
    report = _route_fixture(tmp_path, monkeypatch,
                            doc_heading="## Something else")
    found = _rules_fired(report, "route-contract")
    assert len(found) == 1
    assert "heading matches anchor" in found[0].message


def test_route_contract_stale_registry_entry(tmp_path, monkeypatch):
    extra = """
    "ghost": RouteSpec(env="DELTA_TPU_GHOST",
                       fallback_counter="",
                       doc_anchor=""),"""
    report = _route_fixture(tmp_path, monkeypatch, extra_route=extra)
    found = _rules_fired(report, "route-contract")
    stale = [f for f in found if "stale registry entry" in f.message]
    assert len(stale) == 1 and "'ghost'" in stale[0].message
    # the ghost route has no dispatch funnel / observation either
    assert all("'demo'" not in f.message for f in found)


def test_route_contract_unregistered_route(tmp_path, monkeypatch):
    gate_src = """
import os
from delta_tpu.obs.device import record_gate_decision

ROUTES = {}

def _decide(gate, chosen):
    record_gate_decision(gate, chosen, {}, None, "x")
    return chosen

def demo_route(n):
    return _decide("demo", "host")
"""
    report = _route_fixture(tmp_path, monkeypatch, gate_src=gate_src)
    found = _rules_fired(report, "route-contract")
    assert len(found) == 1
    assert "ROUTES has no 'demo' entry" in found[0].message


def test_route_contract_route_without_gate_record(tmp_path, monkeypatch):
    gate_src = """
import os

ROUTES = {
    "demo": RouteSpec(env="DELTA_TPU_DEMO",
                      fallback_counter="demo.fallbacks",
                      doc_anchor="demo-route"),
}

def demo_route(n):
    return "host"
"""
    report = _route_fixture(tmp_path, monkeypatch, gate_src=gate_src)
    found = _rules_fired(report, "route-contract")
    msgs = "\n".join(f.message for f in found)
    assert "never reaches record_gate_decision" in msgs
    assert "stale registry entry" in msgs


def test_route_contract_silent_without_gate_module(monkeypatch):
    monkeypatch.setenv("DELTA_LINT_GATE_MODULE", "pkg/gate.py")
    report = analyze_sources({"pkg/other.py": "x = 1\n"},
                             rules=["route-contract"])
    assert not report.findings


def test_route_registry_covers_all_routes():
    """The live registry names the five shipped routes and every env
    override is mirrored into the capture-conditions stamp."""
    from delta_tpu.obs.device import CAPTURE_ENV_KEYS
    from delta_tpu.parallel.gate import ROUTES

    assert set(ROUTES) == {"replay", "parse", "decode", "skip", "sql"}
    for spec in ROUTES.values():
        assert spec.env in CAPTURE_ENV_KEYS


# -------------------------------------------------------- recompile-risk


_RECOMPILE_ENV = "DELTA_LINT_RECOMPILE_MODULES"


def test_recompile_risk_unpadded_length_flagged(monkeypatch):
    src = """
import numpy as np
import jax

@jax.jit
def kern(x):
    return x

def launch(vals):
    n = len(vals)
    arr = np.zeros(n, dtype=np.int32)
    return kern(arr)
"""
    monkeypatch.setenv(_RECOMPILE_ENV, "pkg/k.py")
    report = analyze_sources({"pkg/k.py": src}, rules=["recompile-risk"])
    found = _rules_fired(report, "recompile-risk")
    assert len(found) == 1
    assert "'arr'" in found[0].message and "kern" in found[0].message


def test_recompile_risk_padded_length_is_clean(monkeypatch):
    src = """
import numpy as np
import jax
from delta_tpu.ops.replay import pad_bucket

@jax.jit
def kern(x):
    return x

def launch(vals):
    n = len(vals)
    m = pad_bucket(n)
    arr = np.zeros(m, dtype=np.int32)
    return kern(arr)
"""
    monkeypatch.setenv(_RECOMPILE_ENV, "pkg/k.py")
    report = analyze_sources({"pkg/k.py": src}, rules=["recompile-risk"])
    assert not report.findings


def test_recompile_risk_bucket_complement_is_clean(monkeypatch):
    # pad = m - n is the canonical top-up idiom: the concatenated
    # length is bucket-quantized by construction
    src = """
import numpy as np
import jax
from delta_tpu.ops.replay import pad_bucket

@jax.jit
def kern(x):
    return x

def launch(vals, x):
    n = len(vals)
    m = pad_bucket(n)
    pad = m - n
    arr = np.concatenate([x, np.zeros(pad, dtype=x.dtype)])
    return kern(arr)
"""
    monkeypatch.setenv(_RECOMPILE_ENV, "pkg/k.py")
    report = analyze_sources({"pkg/k.py": src}, rules=["recompile-risk"])
    assert not report.findings


def test_recompile_risk_inline_ctor_flagged_once(monkeypatch):
    src = """
import numpy as np
import jax

@jax.jit
def kern(x):
    return x

def launch(vals):
    n = len(vals)
    return kern(np.arange(n))
"""
    monkeypatch.setenv(_RECOMPILE_ENV, "pkg/k.py")
    report = analyze_sources({"pkg/k.py": src}, rules=["recompile-risk"])
    found = _rules_fired(report, "recompile-risk")
    assert len(found) == 1, "one finding per callsite, no duplicates"
    assert "<inline constructor>" in found[0].message


def test_recompile_risk_scalar_asarray_is_clean(monkeypatch):
    # np.asarray(n) is a 0-d operand: data-dependent *value*, constant
    # shape — no recompile risk
    src = """
import numpy as np
import jax

@jax.jit
def kern(x, n):
    return x

def launch(vals, x):
    n = len(vals)
    return kern(x, np.asarray(n))
"""
    monkeypatch.setenv(_RECOMPILE_ENV, "pkg/k.py")
    report = analyze_sources({"pkg/k.py": src}, rules=["recompile-risk"])
    assert not report.findings


def test_recompile_risk_list_accumulator_flagged(monkeypatch):
    src = """
import numpy as np
import jax

@jax.jit
def kern(x):
    return x

def launch(rows):
    out = []
    for r in rows:
        out.append(r.key)
    arr = np.asarray(out)
    return kern(arr)
"""
    monkeypatch.setenv(_RECOMPILE_ENV, "pkg/k.py")
    report = analyze_sources({"pkg/k.py": src}, rules=["recompile-risk"])
    found = _rules_fired(report, "recompile-risk")
    assert len(found) == 1 and "'arr'" in found[0].message


def test_recompile_risk_typed_exemption_honored(monkeypatch):
    src = """
import numpy as np
import jax

@jax.jit
def kern(x):
    return x

def launch(vals):
    n = len(vals)
    return kern(np.arange(n))
"""
    monkeypatch.setenv(_RECOMPILE_ENV, "pkg/k.py")
    monkeypatch.setenv("DELTA_LINT_RECOMPILE_EXEMPT", "pkg/k.py::launch")
    report = analyze_sources({"pkg/k.py": src}, rules=["recompile-risk"])
    assert not report.findings


def test_recompile_risk_uncovered_module_is_silent(monkeypatch):
    src = """
import numpy as np
import jax

@jax.jit
def kern(x):
    return x

def launch(vals):
    n = len(vals)
    return kern(np.arange(n))
"""
    monkeypatch.setenv(_RECOMPILE_ENV, "pkg/other.py")
    report = analyze_sources({"pkg/k.py": src}, rules=["recompile-risk"])
    assert not report.findings


def test_recompile_risk_exemption_registry_names_live_sites():
    """Every built-in exemption must point at a real function — a
    refactor that moves the site must move the exemption with it."""
    import delta_tpu
    from delta_tpu.tools.analyzer.passes.recompile import _EXEMPTIONS

    root = os.path.dirname(os.path.dirname(
        os.path.abspath(delta_tpu.__file__)))
    for site, (kind, reason) in _EXEMPTIONS.items():
        rel, _, qual = site.partition("::")
        assert kind and reason
        path = os.path.join(root, rel)
        assert os.path.exists(path), f"exempt module {rel} is gone"
        leaf = qual.rpartition(".")[2]
        with open(path, encoding="utf-8") as f:
            assert f"def {leaf}(" in f.read(), \
                f"exempt function {site} is gone"


# ------------------------------------------------------- env-knob census


def _env_catalog(tmp_path, monkeypatch, knobs):
    path = tmp_path / "knobs.json"
    path.write_text(json.dumps({"knobs": knobs}, indent=1))
    monkeypatch.setenv("DELTA_LINT_ENV_CATALOG", str(path))
    return path


_ENV_RULES = ["env-knob-uncataloged", "env-knob-dead-entry",
              "env-knob-capture-stamp"]


def test_env_knob_uncataloged_read_flagged(tmp_path, monkeypatch):
    _env_catalog(tmp_path, monkeypatch, {})
    src = 'import os\nV = os.environ.get("DELTA_TPU_FOO")\n'
    report = analyze_sources({"pkg/a.py": src}, rules=_ENV_RULES)
    found = _rules_fired(report, "env-knob-uncataloged")
    assert len(found) == 1
    assert "'DELTA_TPU_FOO'" in found[0].message
    assert found[0].line == 2


def test_env_knob_cataloged_read_is_clean(tmp_path, monkeypatch):
    _env_catalog(tmp_path, monkeypatch, {
        "DELTA_TPU_FOO": {"default": "", "modules": ["pkg/a.py"],
                          "doc": "x", "help": "h"}})
    src = 'import os\nV = os.environ.get("DELTA_TPU_FOO")\n'
    report = analyze_sources({"pkg/a.py": src}, rules=_ENV_RULES)
    assert not report.findings


def test_env_knob_module_drift_flagged(tmp_path, monkeypatch):
    _env_catalog(tmp_path, monkeypatch, {
        "DELTA_TPU_FOO": {"default": "", "modules": ["pkg/other.py"],
                          "doc": "x", "help": "h"}})
    src = 'import os\nV = os.environ.get("DELTA_TPU_FOO")\n'
    other = 'import os\nW = os.environ.get("DELTA_TPU_FOO")\n'
    report = analyze_sources({"pkg/a.py": src, "pkg/other.py": other},
                             rules=["env-knob-uncataloged"])
    found = _rules_fired(report, "env-knob-uncataloged")
    assert len(found) == 1 and found[0].path == "pkg/a.py"
    assert "drifted catalog" in found[0].message


def test_env_knob_dead_entry_flagged(tmp_path, monkeypatch):
    path = _env_catalog(tmp_path, monkeypatch, {
        "DELTA_TPU_FOO": {"default": "", "modules": ["pkg/a.py"],
                          "doc": "x", "help": "h"},
        "DELTA_TPU_GHOST": {"default": "", "modules": [],
                            "doc": "x", "help": "h"}})
    src = 'import os\nV = os.environ.get("DELTA_TPU_FOO")\n'
    report = analyze_sources({"pkg/a.py": src}, rules=_ENV_RULES)
    found = _rules_fired(report, "env-knob-dead-entry")
    assert len(found) == 1
    assert "'DELTA_TPU_GHOST'" in found[0].message
    assert found[0].path == os.path.basename(str(path))


def test_env_knob_dead_entry_modules_list_drift(tmp_path, monkeypatch):
    _env_catalog(tmp_path, monkeypatch, {
        "DELTA_TPU_FOO": {"default": "",
                          "modules": ["pkg/a.py", "pkg/other.py"],
                          "doc": "x", "help": "h"}})
    src = 'import os\nV = os.environ.get("DELTA_TPU_FOO")\n'
    report = analyze_sources({"pkg/a.py": src, "pkg/other.py": "x = 1\n"},
                             rules=["env-knob-dead-entry"])
    found = _rules_fired(report, "env-knob-dead-entry")
    assert len(found) == 1
    assert "'modules' list drifted" in found[0].message


def test_env_knob_const_and_helper_reads_resolved(tmp_path, monkeypatch):
    _env_catalog(tmp_path, monkeypatch, {
        "DELTA_TPU_BAR": {"default": "", "modules": ["pkg/a.py"],
                          "doc": "x", "help": "h"},
        "DELTA_TPU_BAZ": {"default": "1", "modules": ["pkg/a.py"],
                          "doc": "x", "help": "h"}})
    src = """
import os

_ENV = "DELTA_TPU_BAR"

def _env_num(name, default):
    return float(os.environ.get(name, default))

V = os.environ.get(_ENV)
W = _env_num("DELTA_TPU_BAZ", 1)
"""
    report = analyze_sources({"pkg/a.py": src}, rules=_ENV_RULES)
    assert not report.findings, [f.message for f in report.findings]


def test_env_knob_capture_stamp_missing_flagged(tmp_path, monkeypatch):
    _env_catalog(tmp_path, monkeypatch, {
        "DELTA_TPU_FOO": {"default": "", "modules": ["pkg/a.py"],
                          "doc": "x", "help": "h", "capture": True}})
    monkeypatch.setenv("DELTA_LINT_OBS_MODULE", "pkg/obsmod.py")
    src = 'import os\nV = os.environ.get("DELTA_TPU_FOO")\n'
    obsmod = 'CAPTURE_ENV_KEYS = ("DELTA_TPU_OTHER",)\n'
    report = analyze_sources({"pkg/a.py": src, "pkg/obsmod.py": obsmod},
                             rules=["env-knob-capture-stamp"])
    found = _rules_fired(report, "env-knob-capture-stamp")
    assert len(found) == 1
    assert "'DELTA_TPU_FOO'" in found[0].message
    assert found[0].path == "pkg/obsmod.py"


def test_env_knob_capture_stamp_present_is_clean(tmp_path, monkeypatch):
    _env_catalog(tmp_path, monkeypatch, {
        "DELTA_TPU_FOO": {"default": "", "modules": ["pkg/a.py"],
                          "doc": "x", "help": "h", "capture": True}})
    monkeypatch.setenv("DELTA_LINT_OBS_MODULE", "pkg/obsmod.py")
    src = 'import os\nV = os.environ.get("DELTA_TPU_FOO")\n'
    obsmod = 'CAPTURE_ENV_KEYS = ("DELTA_TPU_FOO",)\n'
    report = analyze_sources({"pkg/a.py": src, "pkg/obsmod.py": obsmod},
                             rules=["env-knob-capture-stamp"])
    assert not report.findings


def test_knob_docs_table_is_current():
    """docs/observability.md's generated env-knob table must match
    resources/env_knobs.json — regenerate with
    `python -m delta_tpu.tools.knob_docs` after a catalog edit."""
    from delta_tpu.tools.knob_docs import main as knob_main

    assert knob_main(["--check"]) == 0


def test_capture_conditions_records_route_knobs(monkeypatch):
    """The runtime half of the capture-stamp contract: a knob in
    CAPTURE_ENV_KEYS set in the environment appears in
    capture_conditions()['env']."""
    from delta_tpu.obs.device import capture_conditions

    monkeypatch.setenv("DELTA_TPU_DEVICE_DECODE", "force")
    monkeypatch.setenv("DELTA_TPU_DEVICE_SQL", "1")
    env = capture_conditions()["env"]
    assert env["DELTA_TPU_DEVICE_DECODE"] == "force"
    assert env["DELTA_TPU_DEVICE_SQL"] == "1"


# ------------------------------------- scan cache: catalog soundness


def test_scan_cache_invalidated_by_catalog_edit(tmp_path, monkeypatch):
    """Regression for the stale-cache soundness hole: the pass
    catalogs are scan inputs — editing one must invalidate the cache
    even though no scanned .py file changed."""
    from delta_tpu.tools.analyzer.cache import analyze_paths_cached

    knobs = tmp_path / "knobs.json"
    knobs.write_text(json.dumps({"knobs": {
        "DELTA_TPU_FOO": {"default": "", "modules": [],
                          "doc": "x", "help": "h"}}}))
    monkeypatch.setenv("DELTA_LINT_ENV_CATALOG", str(knobs))
    target = tmp_path / "pkg"
    target.mkdir()
    (target / "a.py").write_text(
        'import os\nV = os.environ.get("DELTA_TPU_FOO")\n')
    cache = tmp_path / "cache.json"
    rules = ["env-knob-uncataloged", "env-knob-dead-entry"]
    r1, s1 = analyze_paths_cached([str(target)], rules=rules,
                                  cache_path=str(cache))
    assert s1["cache"] == "cold" and not r1.findings
    _, s2 = analyze_paths_cached([str(target)], rules=rules,
                                 cache_path=str(cache))
    assert s2["cache"] == "hit"

    # catalog edit, no .py change: must NOT serve the cached report
    knobs.write_text(json.dumps({"knobs": {
        "DELTA_TPU_FOO": {"default": "", "modules": [],
                          "doc": "x", "help": "h"},
        "DELTA_TPU_GHOST": {"default": "", "modules": [],
                            "doc": "x", "help": "h"}}}))
    r3, s3 = analyze_paths_cached([str(target)], rules=rules,
                                  cache_path=str(cache))
    assert s3["cache"] != "hit", \
        "catalog edits must invalidate the scan cache"
    assert _rules_fired(r3, "env-knob-dead-entry")


# ------------------------------------------------------ whole-repo gate


def test_repo_scan_is_clean():
    """The tier-1 gate: zero unsuppressed findings over the installed
    package. Every suppression in the tree is an audited false positive
    or by-design blanket (see docs/static_analysis.md)."""
    import delta_tpu

    pkg = os.path.dirname(os.path.abspath(delta_tpu.__file__))
    report = analyze_paths([pkg], root=os.path.dirname(pkg))
    assert report.files_scanned > 100
    details = "\n".join(
        f"{f.path}:{f.line}: {f.rule}: {f.message}"
        for f in report.findings)
    assert report.ok, f"unsuppressed delta-lint findings:\n{details}"

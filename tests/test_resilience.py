"""delta-resilience unit coverage: the transient/permanent classifier,
RetryPolicy backoff/deadline semantics, the per-endpoint circuit
breaker, the seeded ChaosStore, and the chaos soak (a full workload
under sustained seeded faults must converge to the exact state of a
fault-free run)."""

import numpy as np
import pyarrow as pa
import pytest

import delta_tpu.api as dta
from delta_tpu import obs
from delta_tpu.engine.host import HostEngine
from delta_tpu.errors import (
    CircuitOpenError,
    CommitFailedError,
    LogCorruptedError,
    TableNotFoundError,
)
from delta_tpu.resilience import (
    ChaosSchedule,
    ChaosStore,
    CircuitBreaker,
    RetryPolicy,
    StorageRequestError,
    breaker_for,
    endpoint_of,
    io_call,
    is_transient,
)
from delta_tpu.resilience.chaos import ChaosError
from delta_tpu.storage.logstore import InMemoryLogStore
from delta_tpu.table import Table

# ----------------------------------------------------------- classifier


@pytest.mark.parametrize("exc,expected", [
    (ConnectionError("reset"), True),
    (TimeoutError("slow"), True),
    (OSError("generic io"), True),
    (ChaosError("injected"), True),
    (StorageRequestError("503", status=503), True),
    (StorageRequestError("429", status=429), True),
    (StorageRequestError("connection dropped"), True),  # status=0
    (StorageRequestError("403 forbidden", status=403), False),
    (StorageRequestError("404", status=404), False),
    (FileNotFoundError("gone"), False),
    (FileExistsError("taken"), False),
    (PermissionError("denied"), False),
    (IsADirectoryError("dir"), False),
    (ValueError("bad arg"), False),
    (LogCorruptedError("torn"), False),
    (TableNotFoundError("none"), False),
])
def test_classifier(exc, expected):
    assert is_transient(exc) is expected


def test_classifier_retryable_attribute_wins():
    assert is_transient(CommitFailedError("busy", retryable=True))
    assert not is_transient(CommitFailedError("conflict", retryable=False))
    # an explicit attribute overrides even a normally-permanent type
    e = ValueError("throttled")
    e.retryable = True
    assert is_transient(e)


def test_classifier_commit_failed_exception():
    """Coordinator CommitFailedException: `retryable=True` means the
    TRANSPORT may retry only when it is not a version conflict —
    a conflict must surface to the conflict machinery (rebase at a new
    version), never be replayed verbatim by a retry policy."""
    from delta_tpu.coordinatedcommits import CommitFailedException

    assert is_transient(
        CommitFailedException("busy", retryable=True, conflict=False))
    assert not is_transient(
        CommitFailedException("version taken", retryable=True,
                              conflict=True))
    assert not is_transient(
        CommitFailedException("non-consecutive batch", retryable=False,
                              conflict=False))


def test_classifier_dynamodb_error_types():
    from delta_tpu.storage.dynamodb import DynamoDbError

    assert is_transient(
        DynamoDbError("ProvisionedThroughputExceededException", "slow", 400))
    assert is_transient(DynamoDbError("InternalServerError", "oops", 500))
    assert not is_transient(
        DynamoDbError("ConditionalCheckFailedException", "lost race", 400))


def test_endpoint_of():
    # scheme + authority: breaker state is per bucket/account, so one
    # dead bucket cannot fast-fail every other bucket on the scheme
    assert endpoint_of("gs://bucket/t/_delta_log/0.json") == "gs://bucket"
    assert endpoint_of("gs://other/t/_delta_log/0.json") == "gs://other"
    assert endpoint_of("memory://x/y") == "memory://x"
    assert endpoint_of("memory://x") == "memory://x"
    assert endpoint_of("/local/path") == "file"


# ---------------------------------------------------------- RetryPolicy


def _fake_env(sleeps):
    """Deterministic (sleep, clock) pair: the clock advances only when
    the policy sleeps."""
    now = [0.0]

    def sleep(s):
        sleeps.append(s)
        now[0] += s

    return sleep, lambda: now[0]


def test_retry_transient_until_success():
    sleeps = []
    sleep, clock = _fake_env(sleeps)
    p = RetryPolicy(max_attempts=5, base_s=0.01, cap_s=1.0,
                    deadline_s=60, sleep=sleep, clock=clock)
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise ConnectionError("transient")
        return "ok"

    assert p.call(flaky) == "ok"
    assert calls["n"] == 3
    assert len(sleeps) == 2
    assert all(0.0 <= s <= 1.0 for s in sleeps)


def test_retry_permanent_raises_immediately():
    p = RetryPolicy(max_attempts=5, base_s=0, deadline_s=60)
    calls = {"n": 0}

    def denied():
        calls["n"] += 1
        raise FileNotFoundError("gone")

    with pytest.raises(FileNotFoundError):
        p.call(denied)
    assert calls["n"] == 1


def test_retry_attempt_cap_exhausts():
    sleeps = []
    sleep, clock = _fake_env(sleeps)
    p = RetryPolicy(max_attempts=3, base_s=0.01, cap_s=0.1,
                    deadline_s=60, sleep=sleep, clock=clock)
    calls = {"n": 0}
    x0 = obs.counter("storage.retry.exhausted").value

    def always():
        calls["n"] += 1
        raise TimeoutError("still down")

    with pytest.raises(TimeoutError):
        p.call(always)
    assert calls["n"] == 3
    assert obs.counter("storage.retry.exhausted").value == x0 + 1


def test_retry_wall_clock_deadline():
    sleeps = []
    sleep, clock = _fake_env(sleeps)
    p = RetryPolicy(max_attempts=10_000, base_s=0.5, cap_s=0.5,
                    deadline_s=2.0, sleep=sleep, clock=clock)
    calls = {"n": 0}

    def always():
        calls["n"] += 1
        raise ConnectionError("down")

    with pytest.raises(ConnectionError):
        p.call(always)
    # 0.5s sleeps against a 2s budget: ~5 attempts, nowhere near 10_000
    assert calls["n"] <= 6
    assert sum(sleeps) <= 2.0 + 0.5


def test_retry_on_retry_callback_and_counters():
    sleeps = []
    sleep, clock = _fake_env(sleeps)
    p = RetryPolicy(max_attempts=4, base_s=0.01, cap_s=0.1,
                    deadline_s=60, sleep=sleep, clock=clock)
    seen = []
    a0 = obs.counter("storage.retry.attempts").value
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise ConnectionError("x")
        return 42

    assert p.call(flaky, on_retry=lambda a, e: seen.append(
        (a, type(e).__name__))) == 42
    assert seen == [(1, "ConnectionError"), (2, "ConnectionError")]
    assert obs.counter("storage.retry.attempts").value == a0 + 2


def test_retry_env_knobs(monkeypatch):
    monkeypatch.setenv("DELTA_TPU_RETRY_MAX_ATTEMPTS", "2")
    monkeypatch.setenv("DELTA_TPU_RETRY_BASE_MS", "7")
    monkeypatch.setenv("DELTA_TPU_RETRY_CAP_MS", "70")
    monkeypatch.setenv("DELTA_TPU_RETRY_DEADLINE_S", "3")
    p = RetryPolicy.from_env()
    assert p.max_attempts == 2
    assert p.base_s == pytest.approx(0.007)
    assert p.cap_s == pytest.approx(0.070)
    assert p.deadline_s == 3.0


# ------------------------------------------------------ circuit breaker


def _breaker(threshold=3, reset_s=10.0):
    now = [0.0]
    b = CircuitBreaker("ep", threshold=threshold, reset_s=reset_s,
                       clock=lambda: now[0])
    return b, now


def test_breaker_opens_after_threshold_and_fast_fails():
    b, _now = _breaker(threshold=3)
    for _ in range(3):
        b.before_call()
        b.on_failure()
    assert b.state == "open"
    with pytest.raises(CircuitOpenError) as ei:
        b.before_call()
    assert ei.value.error_class == "DELTA_CIRCUIT_BREAKER_OPEN"


def test_breaker_half_open_probe_success_closes():
    b, now = _breaker(threshold=2, reset_s=5.0)
    for _ in range(2):
        b.before_call()
        b.on_failure()
    assert b.state == "open"
    now[0] = 6.0
    b.before_call()  # the probe
    assert b.state == "half_open"
    b.on_success()
    assert b.state == "closed"
    b.before_call()  # closed again: no gate


def test_breaker_half_open_probe_failure_reopens():
    b, now = _breaker(threshold=2, reset_s=5.0)
    for _ in range(2):
        b.before_call()
        b.on_failure()
    now[0] = 6.0
    b.before_call()
    b.on_failure()
    assert b.state == "open"
    with pytest.raises(CircuitOpenError):
        b.before_call()  # the clock restarted at the failed probe
    now[0] = 12.0
    b.before_call()
    b.on_success()
    assert b.state == "closed"


def test_breaker_half_open_permanent_probe_outcome_closes():
    """A probe answered with a permanent error (e.g. 404 on a log tail
    probe) proves the endpoint is healthy: the policy reports success,
    the circuit closes, and later calls flow. Regression: the probe
    used to stay marked in-flight forever, bricking the endpoint."""
    now = [0.0]
    b = CircuitBreaker("ep-perm", threshold=2, reset_s=5.0,
                       clock=lambda: now[0])
    p = RetryPolicy(max_attempts=2, base_s=0, cap_s=0, deadline_s=60,
                    sleep=lambda s: None)

    def down():
        raise ConnectionError("down")

    with pytest.raises(ConnectionError):
        p.call(down, breaker=b)  # 2 attempts = threshold: opens
    assert b.state == "open"
    now[0] = 6.0
    with pytest.raises(FileNotFoundError):
        p.call(lambda: (_ for _ in ()).throw(FileNotFoundError("404")),
               breaker=b)
    assert b.state == "closed"
    assert p.call(lambda: "ok", breaker=b) == "ok"


def test_breaker_stale_probe_reclaimed_after_reset():
    """Backstop: if a probe's caller dies without reporting an outcome,
    the probe slot is reclaimed after reset_s instead of wedging the
    endpoint until process restart."""
    b, now = _breaker(threshold=2, reset_s=5.0)
    for _ in range(2):
        b.before_call()
        b.on_failure()
    now[0] = 6.0
    b.before_call()  # probe taken, caller never reports back
    with pytest.raises(CircuitOpenError):
        b.before_call()  # in-flight probe still gates
    now[0] = 12.0
    b.before_call()  # stale probe reclaimed
    b.on_success()
    assert b.state == "closed"


def test_breaker_success_resets_failure_streak():
    b, _now = _breaker(threshold=3)
    b.on_failure()
    b.on_failure()
    b.on_success()
    b.on_failure()
    b.on_failure()
    assert b.state == "closed"  # never 3 consecutive


def test_breaker_policy_integration_only_transients_count():
    """Permanent errors pass through the policy without touching the
    breaker; sustained transients trip it and later callers fast-fail."""
    b = CircuitBreaker("ep2", threshold=3, reset_s=60.0)
    p = RetryPolicy(max_attempts=2, base_s=0, cap_s=0, deadline_s=60,
                    sleep=lambda s: None)
    for _ in range(5):
        with pytest.raises(FileNotFoundError):
            p.call(lambda: (_ for _ in ()).throw(
                FileNotFoundError("x")), breaker=b)
    assert b.state == "closed"

    def down():
        raise ConnectionError("down")

    with pytest.raises((ConnectionError, CircuitOpenError)):
        p.call(down, breaker=b)
    with pytest.raises(CircuitOpenError):
        p.call(down, breaker=b)
    calls = {"n": 0}

    def counted():
        calls["n"] += 1

    with pytest.raises(CircuitOpenError):
        p.call(counted, breaker=b)
    assert calls["n"] == 0  # fast fail: fn never invoked


def test_breaker_for_registry_and_env(monkeypatch):
    monkeypatch.setenv("DELTA_TPU_BREAKER_THRESHOLD", "2")
    from delta_tpu import resilience
    resilience.reset()
    b = breaker_for("gs")
    assert b is breaker_for("gs")
    assert b is not breaker_for("abfss")
    assert b.threshold == 2


def test_io_call_funnel():
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] == 1:
            raise ConnectionError("blip")
        return "data"

    assert io_call("memory", flaky) == "data"
    assert calls["n"] == 2


# ------------------------------------------------------------ ChaosStore


def _chaos_store(seed=7, **rates):
    inner = InMemoryLogStore()
    return ChaosStore(inner, ChaosSchedule(seed, **rates),
                      sleep=lambda s: None), inner


def test_chaos_is_deterministic_per_seed():
    logs = []
    for _ in range(2):
        store, _inner = _chaos_store(seed=11, error_rate=0.3)
        for i in range(50):
            try:
                store.write(f"t/_delta_log/{i:020d}.json", b"{}\n")
            except ChaosError:
                pass
        logs.append(list(store.fault_log))
    assert logs[0] == logs[1] and logs[0]


def test_chaos_error_precedes_the_operation():
    """Injected transient errors fire BEFORE the inner op, so a retried
    put-if-absent can never see its own first attempt."""
    store, inner = _chaos_store(seed=3, error_rate=0.5)
    path = "t/_delta_log/00000000000000000000.json"
    for _ in range(20):
        try:
            store.write(path, b"{}\n")
            break
        except ChaosError:
            assert not inner.exists(path)  # nothing leaked
    assert inner.exists(path)


def test_chaos_torn_write_leaves_prefix():
    store, inner = _chaos_store(seed=5, torn_write_rate=1.0)
    path = "t/_delta_log/00000000000000000004.checkpoint.parquet"
    payload = b"P" * 100
    with pytest.raises(ChaosError):
        store.write(path, payload, overwrite=True)
    assert inner.read(path) == payload[:50]
    # commit json files are atomic-by-contract: never torn by default
    store.write("t/_delta_log/00000000000000000000.json", b"{}\n")
    assert inner.read(
        "t/_delta_log/00000000000000000000.json") == b"{}\n"


def test_chaos_ack_loss_lands_then_errors():
    """Ack-loss faults are the deliberate AMBIGUOUS mode: the inner
    write lands first, then the error raises — only for commit .json
    files, whose put-if-absent retry path can detect its own commit."""
    store, inner = _chaos_store(seed=13, ack_loss_rate=1.0)
    path = "t/_delta_log/00000000000000000000.json"
    with pytest.raises(ChaosError):
        store.write(path, b"{}\n")
    assert inner.read(path) == b"{}\n"  # the write landed
    assert store.fault_counts.get("ack_loss") == 1
    # non-commit artifacts are spared: their retries are plain overwrites
    store.write("t/_delta_log/00000000000000000001.checkpoint.parquet",
                b"P", overwrite=True)
    store.write("t/_delta_log/_last_checkpoint", b"{}", overwrite=True)
    assert store.fault_counts.get("ack_loss") == 1


def test_chaos_stale_listing_drops_tail():
    store, _inner = _chaos_store(seed=9, error_rate=0.0,
                                 stale_list_rate=1.0)
    for i in range(6):
        store.write(f"t/_delta_log/{i:020d}.json", b"{}\n")
    listed = [s.path for s in store.list_from("t/_delta_log/")]
    full = [f"t/_delta_log/{i:020d}.json" for i in range(6)]
    assert listed == full[: len(listed)]  # prefix-consistent
    assert len(listed) < 6


def test_chaos_disabled_is_transparent():
    store, _inner = _chaos_store(seed=1, error_rate=1.0,
                                 torn_write_rate=1.0)
    store.enabled = False
    store.write("t/_delta_log/00000000000000000000.json", b"{}\n")
    assert store.read(
        "t/_delta_log/00000000000000000000.json") == b"{}\n"
    assert not store.fault_log


def test_chaos_path_filter_spares_data_io():
    store, _inner = _chaos_store(seed=2, error_rate=1.0)
    store.write("t/part-0001.parquet", b"DATA")  # not _delta_log
    assert store.read("t/part-0001.parquet") == b"DATA"


# ------------------------------------------------------------ chaos soak


def _batch(start, n):
    return pa.table({"x": pa.array(
        np.arange(start, start + n, dtype=np.int64))})


def _chaos_engine(seed, **rates):
    store = ChaosStore(InMemoryLogStore(), ChaosSchedule(seed, **rates),
                       sleep=lambda s: None)

    def resolver(path):
        return store

    return HostEngine(store_resolver=resolver), store


def _workload(eng, path):
    """Write/commit/checkpoint/stream/optimize, end to end."""
    from delta_tpu.streaming import DeltaSink, DeltaSource

    dta.write_table(path, _batch(0, 10), engine=eng)
    sink = DeltaSink(path, query_id="chaos-q", engine=eng)
    for b in range(1, 5):
        sink.add_batch(b, _batch(b * 10, 10))
    t = Table.for_path(path, eng)
    t.checkpoint()
    for b in range(5, 8):
        sink.add_batch(b, _batch(b * 10, 10))
    t.optimize().execute_compaction()
    t.checkpoint()
    streamed = 0
    src = DeltaSource(Table.for_path(path, eng))
    for _off, batch in src.micro_batches():
        streamed += batch.num_rows
    return streamed


def _digest(eng, path):
    """Logical table digest: version + sorted row contents. Stable
    under ANY fault schedule — faults may change which physical files
    hold the rows (a stale listing can make OPTIMIZE plan against an
    older, still-correct snapshot), never the rows themselves."""
    snap = Table.for_path(path, eng).latest_snapshot()
    rows = sorted(dta.read_table(path, engine=eng).column("x").to_pylist())
    return (snap.version, rows)


def _physical_digest(eng, path):
    """Strict digest including physical layout (file count / bytes).
    Holds only for schedules without stale listings: errors, latency,
    and torn writes perturb timing but never what a transaction plans,
    so the replayed log is byte-identical to the fault-free one."""
    snap = Table.for_path(path, eng).latest_snapshot()
    rows = sorted(dta.read_table(path, engine=eng).column("x").to_pylist())
    return (snap.version, snap.num_files, snap.size_in_bytes, rows)


def _run_soak(seed, stale_list_rate=0.05):
    """One seeded chaos run; returns (engine, path, store). Torn writes
    hit checkpoint artifacts/.crc/_last_checkpoint — commit .json files
    are atomic-by-contract on every store (O_EXCL / preconditions), so
    commits see transient errors, lost acks (the write lands, the
    response doesn't — recovered by txnId self-commit detection), and
    stale listings instead."""
    eng, store = _chaos_engine(
        seed, error_rate=0.05, latency_rate=0.02,
        torn_write_rate=0.25, stale_list_rate=stale_list_rate,
        ack_loss_rate=0.1)
    path = f"memory://chaos-{seed}/tbl"
    streamed = _workload(eng, path)
    assert streamed >= 80  # every batch reached the stream reader
    # final verification reads over the SAME store, chaos silenced
    store.enabled = False
    return eng, path, store


def _clean_run(tag):
    clean_eng, _ = _chaos_engine(0, error_rate=0.0)
    clean_path = f"memory://{tag}/tbl"
    _workload(clean_eng, clean_path)
    return clean_eng, clean_path


def test_chaos_soak_converges_to_fault_free_digest():
    """The acceptance property: a seeded chaos run over the full
    workload converges to the same table as a fault-free run."""
    clean_eng, clean_path = _clean_run("fault-free")
    eng, path, store = _run_soak(seed=1234)
    assert store.fault_counts.get("error", 0) > 0, \
        "the schedule must actually have injected faults"
    assert _digest(eng, path) == _digest(clean_eng, clean_path)


def test_chaos_soak_layout_identical_without_stale_listings():
    """With only transient errors, latency, and torn writes (no stale
    listings) the run is byte-identical to fault-free, physical layout
    included — those faults are absorbed before any planning decision."""
    clean_eng, clean_path = _clean_run("fault-free-strict")
    eng, path, store = _run_soak(seed=77, stale_list_rate=0.0)
    assert store.fault_counts.get("error", 0) > 0
    assert (_physical_digest(eng, path)
            == _physical_digest(clean_eng, clean_path))


def test_ack_loss_recovered_as_self_commit():
    """Every commit write's ack is lost after the write lands: the
    put-if-absent retry observes FileExistsError, and CommitInfo.txnId
    self-commit detection recovers each commit at its own version —
    exactly once, no rebase, no duplicated rows, byte-identical log."""
    c0 = obs.counter("txn.self_commit_recovered").value
    clean_eng, clean_path = _clean_run("fault-free-ack")
    eng, store = _chaos_engine(21, error_rate=0.0, ack_loss_rate=1.0)
    path = "memory://ack-loss/tbl"
    _workload(eng, path)
    store.enabled = False
    assert store.fault_counts.get("ack_loss", 0) > 0
    assert obs.counter("txn.self_commit_recovered").value > c0
    assert (_physical_digest(eng, path)
            == _physical_digest(clean_eng, clean_path))


@pytest.mark.slow
def test_chaos_soak_many_seeds():
    """Soak: many seeded schedules, each converging exactly."""
    clean_eng, clean_path = _clean_run("fault-free-soak")
    clean = _digest(clean_eng, clean_path)
    clean_strict = _physical_digest(clean_eng, clean_path)

    for seed in range(20):
        eng, path, _store = _run_soak(seed=seed)
        assert _digest(eng, path) == clean, \
            f"divergence under chaos seed {seed}"

    for seed in range(10):
        eng, path, _store = _run_soak(seed=seed + 100,
                                      stale_list_rate=0.0)
        assert _physical_digest(eng, path) == clean_strict, \
            f"layout divergence under stale-free chaos seed {seed + 100}"


# ------------------------------------------- breaker half-open races


def test_breaker_half_open_admits_exactly_one_concurrent_probe():
    """Race: N threads hit a cooled-down open breaker at once; exactly
    one wins the probe slot, the rest fast-fail typed."""
    import threading

    b, now = _breaker(threshold=1, reset_s=5.0)
    b.before_call()
    b.on_failure()
    assert b.state == "open"
    now[0] = 6.0  # cooled down: next call becomes the probe

    barrier = threading.Barrier(8)
    outcomes = []
    lock = threading.Lock()

    def contender():
        barrier.wait()
        try:
            b.before_call()
            with lock:
                outcomes.append("probe")
        except CircuitOpenError:
            with lock:
                outcomes.append("fast-fail")

    threads = [threading.Thread(target=contender) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert outcomes.count("probe") == 1
    assert outcomes.count("fast-fail") == 7
    b.on_success()  # the winner reports back
    assert b.state == "closed"


def test_breaker_half_open_late_success_after_reopen_still_closes():
    """Race: probe A is reclaimed as stale, probe B fails and re-opens
    the circuit — then A's slow success finally lands. on_success is
    authoritative (the endpoint answered), so the circuit closes; a
    wedged-open circuit would need another full cooldown for no
    reason."""
    b, now = _breaker(threshold=1, reset_s=5.0)
    b.before_call()
    b.on_failure()
    now[0] = 6.0
    b.before_call()           # probe A admitted, caller stalls
    now[0] = 12.0
    b.before_call()           # A stale -> reclaimed by probe B
    b.on_failure()            # B fails: re-open, clock restarts
    assert b.state == "open"
    b.on_success()            # A's success finally lands
    assert b.state == "closed"
    b.before_call()           # and calls flow again


def test_breaker_concurrent_failures_trip_exactly_once():
    """Race: threshold-many concurrent failures must produce one open
    transition (one `storage.breaker.opens` bump), not one per racer."""
    import threading

    opens = obs.counter("storage.breaker.opens").value
    b = CircuitBreaker("ep-race", threshold=4, reset_s=60.0)
    barrier = threading.Barrier(4)

    def failer():
        barrier.wait()
        b.on_failure()

    threads = [threading.Thread(target=failer) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert b.state == "open"
    assert obs.counter("storage.breaker.opens").value == opens + 1


# --------------------------------------------------- deadline edges


def test_deadline_zero_budget_is_immediately_expired():
    from delta_tpu import resilience
    from delta_tpu.errors import DeadlineExceededError

    with resilience.deadline_scope(0):
        assert resilience.expired()
        assert resilience.remaining() <= 0
        with pytest.raises(DeadlineExceededError):
            resilience.check_deadline("unit probe")


def test_deadline_negative_budget_clamps_to_zero():
    from delta_tpu import resilience

    import time as _time

    t0 = _time.monotonic()
    with resilience.deadline_scope(-30.0) as at:
        assert at is not None
        assert at <= t0 + 1.0  # clamped to "now", not 30s in the past
        assert resilience.expired()


def test_deadline_none_is_transparent():
    from delta_tpu import resilience

    assert resilience.current_deadline() is None
    assert resilience.remaining() is None
    assert not resilience.expired()
    resilience.check_deadline("no ambient budget")  # never raises
    with resilience.deadline_scope(60):
        outer = resilience.current_deadline()
        with resilience.deadline_scope(None) as at:
            # None scope: the enclosing budget stays in force
            assert at == outer
            assert resilience.current_deadline() == outer


def test_deadline_nested_scope_only_tightens():
    from delta_tpu import resilience

    with resilience.deadline_scope(0.05) as outer:
        with resilience.deadline_scope(60.0) as inner:
            # the callee cannot outlive the caller's budget
            assert inner == outer
        with resilience.deadline_scope(0.001) as tighter:
            assert tighter < outer
        assert resilience.current_deadline() == outer
    assert resilience.current_deadline() is None


def test_deadline_scope_at_past_instant_expired():
    import time as _time

    from delta_tpu import resilience
    from delta_tpu.errors import DeadlineExceededError

    with resilience.deadline_scope_at(_time.monotonic() - 1.0):
        assert resilience.expired()
        assert resilience.remaining() < 0
        with pytest.raises(DeadlineExceededError):
            resilience.check_deadline()
    # reset token restored the clean ambient state
    assert resilience.current_deadline() is None


def test_deadline_scope_at_respects_enclosing_budget():
    import time as _time

    from delta_tpu import resilience

    with resilience.deadline_scope(0.05) as outer:
        with resilience.deadline_scope_at(
                _time.monotonic() + 60.0) as at:
            assert at == outer


def test_expired_deadline_aborts_retry_before_first_attempt():
    """The policy must not burn a single attempt once the ambient
    budget is gone — abandonment happens at the attempt boundary."""
    from delta_tpu import resilience
    from delta_tpu.errors import DeadlineExceededError

    attempts = []
    p = RetryPolicy(max_attempts=5, base_s=0, cap_s=0, deadline_s=60,
                    sleep=lambda s: None)
    with resilience.deadline_scope(0):
        with pytest.raises(DeadlineExceededError):
            p.call(lambda: attempts.append(1))
    assert not attempts

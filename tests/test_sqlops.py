"""Device SQL operator kernels (`ops/sqlops.py`) vs host oracles:
sort permutation vs numpy lexsort, group-by reductions vs pandas
groupby, join pair expansion vs pandas merge, window rank family and
running frames vs pandas transforms. These are the unit layer under
the TPC-DS corpus parity tests (test_tpcds.py runs the full engine on
both substrates)."""

import numpy as np
import pandas as pd
import pytest

from delta_tpu.ops.sqlops import (
    GroupAggregator,
    join_pairs,
    sort_permutation,
    window_peer_last,
    window_ranks,
    window_running,
)


@pytest.fixture
def rng():
    return np.random.default_rng(42)


# ------------------------------------------------------------- sort --

def test_sort_permutation_single_key(rng):
    v = rng.standard_normal(10_000)
    perm = sort_permutation([v])
    assert np.array_equal(v[perm], np.sort(v))


def test_sort_permutation_multi_key_stable(rng):
    a = rng.integers(0, 50, 5_000).astype(np.int64)
    b = rng.standard_normal(5_000)
    perm = sort_permutation([a, b])
    ref = np.lexsort((b, a))
    assert np.array_equal(perm, ref)


def test_sort_permutation_stability_on_ties(rng):
    a = rng.integers(0, 10, 4_000).astype(np.int64)
    perm = sort_permutation([a])
    # stable: equal keys keep original relative order
    ref = np.argsort(a, kind="stable")
    assert np.array_equal(perm, ref)


def test_sort_permutation_empty():
    assert len(sort_permutation([np.empty(0, np.float64)])) == 0


# --------------------------------------------------------- group-by --

def _pd_group(codes, values, valid, op):
    s = pd.Series(np.where(valid, values.astype(float), np.nan))
    g = s.groupby(codes)
    if op == "sum":
        return g.sum(min_count=1)
    if op == "count":
        return g.count()
    return getattr(g, op)()


@pytest.mark.parametrize("op", ["sum", "count", "min", "max"])
def test_group_reduce_float(rng, op):
    n, G = 50_000, 700
    codes = rng.integers(0, G, n).astype(np.int32)
    v = rng.standard_normal(n) * 1e3
    valid = rng.random(n) > 0.1
    ga = GroupAggregator(codes, G)
    agg, cnt = ga.reduce(v, valid, op)
    ref = _pd_group(codes, v, valid, op).reindex(range(G))
    got = agg.astype(float).copy()
    got[cnt == 0] = np.nan
    if op == "count":
        got = agg.astype(float)  # count of empty group = 0, not NaN
        ref = ref.fillna(0)
    np.testing.assert_allclose(got, ref.to_numpy(), rtol=1e-12,
                               equal_nan=True)


def test_group_reduce_int_exact(rng):
    # int64 accumulation must be exact where f64 would round
    n = 100
    codes = np.zeros(n, np.int32)
    v = np.full(n, (1 << 53) + 1, np.int64)  # not representable in f64
    ga = GroupAggregator(codes, 1)
    agg, cnt = ga.reduce(v, np.ones(n, bool), "sum")
    assert agg[0] == ((1 << 53) + 1) * n
    assert cnt[0] == n


def test_group_sizes_and_all_null_group(rng):
    codes = np.array([0, 0, 1, 2, 2, 2], np.int32)
    v = np.arange(6, dtype=np.float64)
    valid = np.array([True, True, False, True, True, True])
    ga = GroupAggregator(codes, 3)
    assert ga.sizes().tolist() == [2, 1, 3]
    agg, cnt = ga.reduce(v, valid, "sum")
    assert cnt.tolist() == [2, 0, 3]  # group 1 is all-null -> NULL sum


def test_group_var_two_pass(rng):
    n, G = 20_000, 40
    codes = rng.integers(0, G, n).astype(np.int32)
    # large offset: single-pass sumsq would lose precision
    v = rng.standard_normal(n) + 1e8
    valid = rng.random(n) > 0.05
    ga = GroupAggregator(codes, G)
    var, cnt = ga.var(v, valid)
    ref = pd.Series(np.where(valid, v, np.nan)).groupby(codes).var()
    np.testing.assert_allclose(var, ref.to_numpy(), rtol=1e-6,
                               equal_nan=True)


def test_group_count_distinct(rng):
    n, G = 30_000, 100
    codes = rng.integers(0, G, n).astype(np.int32)
    vals = rng.integers(0, 50, n)
    valid = rng.random(n) > 0.2
    ga = GroupAggregator(codes, G)
    got = ga.count_distinct(vals, valid)
    ref = (pd.DataFrame({"g": codes,
                         "v": np.where(valid, vals.astype(float),
                                       np.nan)})
           .groupby("g")["v"].nunique().reindex(range(G), fill_value=0))
    assert got.tolist() == ref.astype(int).tolist()


# ------------------------------------------------------------- join --

def _pd_join(lk, rk, how):
    left = pd.DataFrame({"k": lk, "li": np.arange(len(lk))})
    right = pd.DataFrame({"k": rk, "ri": np.arange(len(rk))})
    out = left.merge(right, on="k", how=how)
    li = out["li"].fillna(-1).astype(np.int64)
    ri = out["ri"].fillna(-1).astype(np.int64)
    return set(zip(li.tolist(), ri.tolist()))


@pytest.mark.parametrize("how", ["inner", "left", "right", "outer"])
def test_join_pairs_vs_pandas(rng, how):
    lk = rng.integers(0, 500, 3_000).astype(np.uint32)
    rk = rng.integers(200, 700, 2_000).astype(np.uint32)
    li, ri = join_pairs(lk, rk, how=how)
    assert set(zip(li.tolist(), ri.tolist())) == _pd_join(lk, rk, how)


def test_join_pairs_many_to_many(rng):
    lk = np.array([1, 1, 2, 3], np.uint32)
    rk = np.array([1, 1, 1, 3, 4], np.uint32)
    li, ri = join_pairs(lk, rk, how="inner")
    # key 1: 2x3 pairs; key 3: 1
    assert len(li) == 7
    assert set(zip(li.tolist(), ri.tolist())) == {
        (0, 0), (0, 1), (0, 2), (1, 0), (1, 1), (1, 2), (3, 3)}


def test_join_pairs_empty_sides():
    e = np.empty(0, np.uint32)
    k = np.array([1, 2], np.uint32)
    li, ri = join_pairs(e, k, how="inner")
    assert len(li) == 0
    li, ri = join_pairs(e, k, how="outer")
    assert set(ri.tolist()) == {0, 1} and set(li.tolist()) == {-1}
    li, ri = join_pairs(k, e, how="left")
    assert set(li.tolist()) == {0, 1} and set(ri.tolist()) == {-1}


# ---------------------------------------------------------- windows --

def _boundaries(parts, keys):
    n = len(parts[0]) if parts else len(keys[0])
    pb = np.zeros(n, bool)
    pb[0] = True
    for p in parts:
        pb[1:] |= p[1:] != p[:-1]
    kb = pb.copy()
    for k in keys:
        kb[1:] |= k[1:] != k[:-1]
    return pb, kb


def test_window_ranks_vs_pandas(rng):
    n = 20_000
    part = np.sort(rng.integers(0, 300, n))
    key = rng.integers(0, 20, n)
    # sort within partitions by key (contiguity contract)
    order = np.lexsort((key, part))
    part, key = part[order], key[order]
    pb, kb = _boundaries([part], [key])
    rn, rk, dr = window_ranks(pb, kb)
    df = pd.DataFrame({"p": part, "k": key})
    g = df.groupby("p")["k"]
    assert np.array_equal(rn, g.cumcount().to_numpy() + 1)
    assert np.array_equal(rk, g.rank(method="min").astype(int)
                          .to_numpy())
    assert np.array_equal(dr, g.rank(method="dense").astype(int)
                          .to_numpy())


@pytest.mark.parametrize("op", ["sum", "mean", "min", "max", "count"])
def test_window_running_vs_pandas(rng, op):
    n = 10_000
    part = np.sort(rng.integers(0, 100, n))
    v = rng.standard_normal(n)
    valid = rng.random(n) > 0.1
    pb = np.zeros(n, bool)
    pb[0] = True
    pb[1:] = part[1:] != part[:-1]
    got, cnt = window_running(v, valid, pb, op)
    s = pd.Series(np.where(valid, v, np.nan))
    expand = {"sum": lambda x: x.expanding().sum(),
              "mean": lambda x: x.expanding().mean(),
              "min": lambda x: x.expanding().min(),
              "max": lambda x: x.expanding().max(),
              "count": lambda x: x.expanding().count()}[op]
    ref = s.groupby(part).transform(expand).to_numpy()
    got = got.copy()
    if op != "count":
        got[cnt == 0] = np.nan
    np.testing.assert_allclose(got, np.nan_to_num(ref, nan=np.nan),
                               rtol=1e-9, equal_nan=True)


def test_window_peer_last(rng):
    # RANGE frame: peers (equal order keys) share the run's last value
    vals = np.array([1.0, 2.0, 3.0, 4.0, 5.0])
    cnts = np.array([1, 2, 3, 4, 5], np.int64)
    kb = np.array([True, False, True, False, False])
    v, c = window_peer_last(vals, cnts, kb)
    assert v.tolist() == [2.0, 2.0, 5.0, 5.0, 5.0]
    assert c.tolist() == [2, 2, 5, 5, 5]


def test_x64_flip_coexists_with_replay_kernels(rng):
    # sqlops enables jax_enable_x64 lazily; the replay kernels are
    # dtype-explicit and must produce identical masks afterwards
    from delta_tpu.ops.replay import python_replay_reference, replay_select

    sort_permutation([rng.standard_normal(64)])  # flips x64 on
    n = 20_000
    pk = rng.integers(0, 2_000, n).astype(np.uint32)
    dk = np.zeros(n, np.uint32)
    ver = np.sort(rng.integers(0, 500, n)).astype(np.int32)
    change = np.nonzero(np.diff(ver))[0] + 1
    starts = np.concatenate([[0], change])
    lens = np.diff(np.concatenate([starts, [n]]))
    order = (np.arange(n) - np.repeat(starts, lens)).astype(np.int32)
    is_add = rng.random(n) < 0.7
    live, tomb = replay_select([pk, dk], ver, order, is_add)
    live_o, tomb_o = python_replay_reference(
        list(zip(pk.tolist(), dk.tolist())), ver, order, is_add)
    assert np.array_equal(np.asarray(live), live_o)
    assert np.array_equal(np.asarray(tomb), tomb_o)


def test_sort_permutation_bool_null_lane(rng):
    # the documented null-ordering lane pattern: bool lanes must work
    v = np.array([3.0, np.nan, 1.0, np.nan, 2.0])
    null_lane = np.isnan(v)  # NULLS LAST ascending
    perm = sort_permutation([null_lane, np.nan_to_num(v, nan=0.0)])
    assert perm.tolist() == [2, 4, 0, 1, 3]


def test_window_peer_last_first_run_unflagged():
    # a raw diff-based kb lane may leave row 0 unflagged; the first
    # run must not wrap into the padding segment
    vals = np.array([1.0, 2.0, 3.0, 4.0])
    cnts = np.array([1, 2, 3, 4], np.int64)
    kb = np.array([False, False, True, False])
    v, c = window_peer_last(vals, cnts, kb)
    assert v.tolist() == [2.0, 2.0, 4.0, 4.0]
    assert c.tolist() == [2, 2, 4, 4]

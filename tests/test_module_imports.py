"""Every module must import cleanly, and no function may reference an
undefined module-level name. Guards against the class of bug where a
helper is called but never defined (it only explodes when that code path
runs — e.g. the r05 catalog `_check_create_spec_matches` gap, which
broke collection of an entire test file).

The dynamic import walk stays here (it exercises real import-time side
effects the static pass can't); the undefined-name check is now a thin
wrapper over delta-lint's ``undefined-name`` rule
(``delta_tpu/tools/analyzer/passes/imports.py``), which absorbed the
old symtable logic — one implementation, shared by CI and this test."""

import importlib
import os
import pkgutil

import pytest

import delta_tpu
from delta_tpu.tools.analyzer import analyze_paths

MODULES = sorted(
    m.name for m in pkgutil.walk_packages(delta_tpu.__path__,
                                          prefix="delta_tpu."))


def test_every_module_imports():
    failures = []
    for name in MODULES:
        try:
            importlib.import_module(name)
        except ImportError as e:
            # optional backends (cloud SDKs etc.) may be absent in the
            # test container; anything else is a real break
            msg = str(e)
            if "delta_tpu" in msg:
                failures.append(f"{name}: {e}")
        except Exception as e:  # noqa: BLE001 - any other error is a bug
            failures.append(f"{name}: {type(e).__name__}: {e}")
    assert not failures, "\n".join(failures)


def test_no_undefined_names():
    pkg = os.path.dirname(os.path.abspath(delta_tpu.__file__))
    report = analyze_paths([pkg], root=os.path.dirname(pkg),
                           rules=["undefined-name"])
    problems = [f"{f.path}:{f.line}: {f.message}" for f in report.findings]
    assert not problems, "\n".join(problems)
    assert report.files_scanned > 100  # the walk actually covered the tree


@pytest.mark.parametrize("helper", ["_check_create_spec_matches"])
def test_regression_catalog_helpers_defined(helper):
    import delta_tpu.catalog as cat

    assert callable(getattr(cat, helper))

"""Every module must import cleanly, and no function may reference an
undefined module-level name. Guards against the class of bug where a
helper is called but never defined (it only explodes when that code path
runs — e.g. the r05 catalog `_check_create_spec_matches` gap, which
broke collection of an entire test file).

The undefined-name check uses pyflakes when installed (the `lint` extra
in pyproject.toml); otherwise a stdlib `symtable` fallback covers the
same class: names a function scope resolves globally that exist neither
at module level nor in builtins."""

import builtins
import importlib
import pkgutil
import symtable

import pytest

import delta_tpu

MODULES = sorted(
    m.name for m in pkgutil.walk_packages(delta_tpu.__path__,
                                          prefix="delta_tpu."))


def test_every_module_imports():
    failures = []
    for name in MODULES:
        try:
            importlib.import_module(name)
        except ImportError as e:
            # optional backends (cloud SDKs etc.) may be absent in the
            # test container; anything else is a real break
            msg = str(e)
            if "delta_tpu" in msg:
                failures.append(f"{name}: {e}")
        except Exception as e:  # noqa: BLE001 - any other error is a bug
            failures.append(f"{name}: {type(e).__name__}: {e}")
    assert not failures, "\n".join(failures)


def _module_files():
    import os

    root = os.path.dirname(delta_tpu.__file__)
    for dirpath, _dirs, files in os.walk(root):
        for f in sorted(files):
            if f.endswith(".py"):
                yield os.path.join(dirpath, f)


_BUILTINS = set(dir(builtins)) | {
    "__file__", "__name__", "__doc__", "__package__", "__spec__",
    "__loader__", "__builtins__", "__annotations__", "__class__",
    "__debug__", "__path__", "WindowsError",
}


def _undefined_globals(path: str):
    """symtable-based: a symbol a nested scope resolves as GLOBAL must be
    bound at module level (imports, defs, assignments — symtable records
    bindings from every branch, so conditional imports count) or be a
    builtin."""
    with open(path) as f:
        src = f.read()
    try:
        table = symtable.symtable(src, path, "exec")
    except SyntaxError as e:  # pragma: no cover - would break import too
        return [f"{path}: syntax error {e}"]
    module_names = set(table.get_identifiers())
    problems = []

    def walk(t):
        if t.get_type() == "function":
            for sym in t.get_symbols():
                if (sym.is_referenced() and sym.is_global()
                        and not sym.is_assigned()
                        and sym.get_name() not in module_names
                        and sym.get_name() not in _BUILTINS):
                    problems.append(
                        f"{path}: {t.get_name()}() references undefined "
                        f"name {sym.get_name()!r}")
        for child in t.get_children():
            walk(child)

    walk(table)
    return problems


def test_no_undefined_names():
    try:
        from pyflakes.api import checkPath  # noqa: F401
        from pyflakes.reporter import Reporter

        import io

        out, err = io.StringIO(), io.StringIO()
        rep = Reporter(out, err)
        n = sum(checkPath(p, rep) for p in _module_files())
        undefined = [line for line in out.getvalue().splitlines()
                     if "undefined name" in line]
        assert not undefined, "\n".join(undefined)
        assert n >= 0
    except ImportError:
        problems = []
        for p in _module_files():
            problems.extend(_undefined_globals(p))
        assert not problems, "\n".join(problems)


@pytest.mark.parametrize("helper", ["_check_create_spec_matches"])
def test_regression_catalog_helpers_defined(helper):
    import delta_tpu.catalog as cat

    assert callable(getattr(cat, helper))

"""TpuEngine with a multi-device mesh: full snapshot load through the
sharded replay path."""

import numpy as np
import pyarrow as pa

import delta_tpu.api as dta
from delta_tpu.engine.host import HostEngine
from delta_tpu.engine.tpu import TpuEngine
from delta_tpu.parallel import make_mesh
from delta_tpu.table import Table


def test_snapshot_with_mesh_engine(tmp_table_path):
    for i in range(5):
        data = pa.table({"id": pa.array(np.arange(i * 50, (i + 1) * 50, dtype=np.int64))})
        dta.write_table(tmp_table_path, data)
    from delta_tpu.commands.dml import delete
    from delta_tpu.expressions import col, lit

    delete(Table.for_path(tmp_table_path), col("id") < lit(25))

    mesh_engine = TpuEngine(mesh=make_mesh())
    snap = Table.for_path(tmp_table_path, mesh_engine).latest_snapshot()
    host_snap = Table.for_path(tmp_table_path, HostEngine()).latest_snapshot()
    assert snap.num_files == host_snap.num_files
    assert snap.size_in_bytes == host_snap.size_in_bytes
    assert sorted(snap.state.add_files_table.column("path").to_pylist()) == sorted(
        host_snap.state.add_files_table.column("path").to_pylist()
    )
    out = snap.scan().to_arrow()
    assert out.num_rows == 225

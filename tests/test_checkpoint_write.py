"""Checkpoint WRITE path (`log/checkpointer.py`, `write/ckpt_pipeline.py`,
`ops/stats.py`): write→read digest parity across checkpoint policy ×
stats mode × full/incremental, part-reuse correctness (only the changed
tail is rewritten), torn-multipart abort + orphan cleanup, the pipeline
profitability gate both ways, stats-kernel host/device parity, and DV
device-packing byte equality."""

import json
import os

import numpy as np
import pytest

from delta_tpu import obs
from delta_tpu.config import settings
from delta_tpu.engine.host import HostEngine
from delta_tpu.log.checkpointer import write_checkpoint
from delta_tpu.log.last_checkpoint import read_last_checkpoint
from delta_tpu.replay.columnar import clear_parse_cache
from delta_tpu.resilience.chaos import ChaosError, ChaosSchedule, ChaosStore
from delta_tpu.storage import InMemoryLogStore
from delta_tpu.table import Table
from delta_tpu.write import ckpt_pipeline

PROTOCOL = {"protocol": {"minReaderVersion": 1, "minWriterVersion": 2}}
METADATA = {
    "metaData": {
        "id": "ckpt-write-test-table",
        "format": {"provider": "parquet", "options": {}},
        "schemaString": json.dumps(
            {"type": "struct",
             "fields": [{"name": "x", "type": "long", "nullable": True,
                         "metadata": {}}]}),
        "partitionColumns": [],
        "configuration": {},
    }
}

PARTS_WRITTEN = obs.counter("checkpoint.parts_written")
PARTS_REUSED = obs.counter("checkpoint.parts_reused")
ABORTED = obs.counter("checkpoint.aborted_writes")


@pytest.fixture(autouse=True)
def _fresh():
    old_part_size = settings.checkpoint_part_size
    clear_parse_cache()
    yield
    settings.checkpoint_part_size = old_part_size
    clear_parse_cache()


def _add(path, size=100):
    return {"add": {"path": path, "partitionValues": {}, "size": size,
                    "modificationTime": 1000, "dataChange": True,
                    "stats": json.dumps({"numRecords": size // 10})}}


def _commit_actions(v, per=5):
    return [_add(f"part-{v:04d}-{i}.parquet", size=100 + v + i)
            for i in range(per)]


def _write_commit_local(log, v, actions):
    os.makedirs(log, exist_ok=True)
    with open(os.path.join(log, f"{v:020d}.json"), "w") as f:
        for a in actions:
            f.write(json.dumps(a) + "\n")


def _build_local_log(path, ncommits, per=5):
    log = os.path.join(str(path), "_delta_log")
    _write_commit_local(log, 0, [PROTOCOL, METADATA])
    for v in range(1, ncommits + 1):
        _write_commit_local(log, v, _commit_actions(v, per))
    return log


def _append_local(log, versions, per=5):
    for v in versions:
        _write_commit_local(log, v, _commit_actions(v, per))


def _digest(path, eng=None):
    """Everything a checkpoint must preserve: the live file set with
    stats, P&M, txns, and domains (per-row replay versions are
    deliberately excluded — a checkpoint collapses them)."""
    clear_parse_cache()
    snap = Table.for_path(str(path), eng or HostEngine()).latest_snapshot()
    st = snap.state
    at = st.add_files_table
    rows = sorted(zip(at.column("path").to_pylist(),
                      at.column("size").to_pylist(),
                      at.column("stats").to_pylist()))
    return (snap.version, st.num_files,
            (snap.protocol.minReaderVersion, snap.protocol.minWriterVersion),
            snap.metadata.id,
            sorted((k, t.version) for k, t in st.set_transactions.items()),
            sorted((k, d.configuration, d.removed)
                   for k, d in st.domain_metadata.items()),
            rows)


def _drop_commits(log, through_version):
    for v in range(through_version + 1):
        p = os.path.join(log, f"{v:020d}.json")
        if os.path.exists(p):
            os.remove(p)


# --------------------------------------------- write→read parity matrix


@pytest.mark.parametrize("policy,part_size", [
    ("classic", None),
    ("multipart", 12),
    ("v2", 12),
])
@pytest.mark.parametrize("device_stats", ["0", "1"])
@pytest.mark.parametrize("incremental", [False, True])
def test_digest_parity_matrix(tmp_path, monkeypatch, policy, part_size,
                              device_stats, incremental):
    """Reloading purely from the checkpoint reproduces the live state,
    for every policy × stats-mode × full/incremental combination."""
    monkeypatch.setenv("DELTA_TPU_DEVICE_CKPT_STATS", device_stats)
    log = _build_local_log(tmp_path, 10)
    settings.checkpoint_part_size = part_size
    eng = HostEngine()
    write_policy = "v2" if policy == "v2" else None

    snap = Table.for_path(str(tmp_path), eng).latest_snapshot()
    write_checkpoint(eng, snap, policy=write_policy)
    version = 10
    if incremental:
        _append_local(log, [11, 12])
        snap = Table.for_path(str(tmp_path), eng).latest_snapshot()
        prev = read_last_checkpoint(eng.fs, log)
        write_checkpoint(eng, snap, policy=write_policy, prev_info=prev)
        version = 12

    live = _digest(tmp_path, eng)
    _drop_commits(log, version)
    reloaded = _digest(tmp_path, eng)
    assert reloaded == live
    assert reloaded[0] == version and reloaded[1] == 5 * version


def test_host_device_checkpoints_byte_identical(tmp_path, monkeypatch):
    """Stat mode is telemetry only: flipping it may not change a single
    checkpoint byte (host and device aggregates are bit-identical and
    neither enters the fingerprints)."""
    log = _build_local_log(tmp_path, 6)
    settings.checkpoint_part_size = 12
    eng = HostEngine()

    def ckpt_bytes(mode):
        monkeypatch.setenv("DELTA_TPU_DEVICE_CKPT_STATS", mode)
        snap = Table.for_path(str(tmp_path), eng).latest_snapshot()
        write_checkpoint(eng, snap)
        out = {}
        for f in sorted(os.listdir(log)):
            if ".checkpoint." in f:
                with open(os.path.join(log, f), "rb") as fh:
                    out[f] = fh.read()
                os.remove(os.path.join(log, f))
        os.remove(os.path.join(log, "_last_checkpoint"))
        return out

    host = ckpt_bytes("0")
    dev = ckpt_bytes("1")
    assert set(host) == set(dev)
    for name in host:
        assert host[name] == dev[name], name


# ------------------------------------------------------ incremental reuse


def test_multipart_reuse_only_tail_rewritten(tmp_path):
    """Append-only growth: full earlier chunks byte-copy from the
    previous checkpoint; only the small-actions part and the changed
    tail chunk are re-serialized."""
    log = _build_local_log(tmp_path, 10)  # 50 files
    settings.checkpoint_part_size = 12    # chunks: 12,12,12,12,2
    eng = HostEngine()

    snap = Table.for_path(str(tmp_path), eng).latest_snapshot()
    w0, r0 = PARTS_WRITTEN.value, PARTS_REUSED.value
    info1 = write_checkpoint(eng, snap)
    assert PARTS_WRITTEN.value - w0 == 6  # small-actions + 5 file chunks
    assert PARTS_REUSED.value - r0 == 0
    assert info1.partManifest is not None
    assert len(info1.partManifest["parts"]) == 5

    _append_local(log, [11, 12])          # 60 files -> chunks: 12 x 5
    snap2 = Table.for_path(str(tmp_path), eng).latest_snapshot()
    prev = read_last_checkpoint(eng.fs, log)
    assert prev is not None and prev.partManifest is not None
    w1, r1 = PARTS_WRITTEN.value, PARTS_REUSED.value
    info2 = write_checkpoint(eng, snap2, prev_info=prev)
    # 4 full chunks unchanged (fixed boundaries), tail chunk grew
    assert PARTS_REUSED.value - r1 == 4
    assert PARTS_WRITTEN.value - w1 == 6  # byte-copies still materialize
    fp1 = {e["fp"] for e in info1.partManifest["parts"]}
    fp2 = {e["fp"] for e in info2.partManifest["parts"]}
    assert len(fp1 & fp2) == 4

    _drop_commits(log, 12)
    assert _digest(tmp_path, eng)[1] == 60


def test_v2_sidecar_reuse_rereferences_in_place(tmp_path):
    """V2 reuse writes nothing: fingerprint-matched sidecars are
    pointed at again, so consecutive checkpoints share sidecar files."""
    log = _build_local_log(tmp_path, 10)
    settings.checkpoint_part_size = 12
    eng = HostEngine()

    snap = Table.for_path(str(tmp_path), eng).latest_snapshot()
    info1 = write_checkpoint(eng, snap, policy="v2")
    sidecar_dir = os.path.join(log, "_sidecars")
    first = set(os.listdir(sidecar_dir))
    assert len(first) == 5

    _append_local(log, [11, 12])
    snap2 = Table.for_path(str(tmp_path), eng).latest_snapshot()
    prev = read_last_checkpoint(eng.fs, log)
    w1, r1 = PARTS_WRITTEN.value, PARTS_REUSED.value
    info2 = write_checkpoint(eng, snap2, policy="v2", prev_info=prev)
    assert PARTS_REUSED.value - r1 == 4
    assert PARTS_WRITTEN.value - w1 == 1  # only the changed tail sidecar
    names2 = {e["name"] for e in info2.partManifest["parts"]}
    assert len(names2 & first) == 4       # re-referenced, not copied
    assert len(set(os.listdir(sidecar_dir))) == 6

    _drop_commits(log, 12)
    assert _digest(tmp_path, eng)[1] == 60


def test_config_change_invalidates_reuse(tmp_path):
    """A different part size produces a different writer fingerprint —
    the old manifest must be ignored, never misapplied."""
    log = _build_local_log(tmp_path, 10)
    settings.checkpoint_part_size = 12
    eng = HostEngine()
    snap = Table.for_path(str(tmp_path), eng).latest_snapshot()
    write_checkpoint(eng, snap)

    settings.checkpoint_part_size = 10
    _append_local(log, [11])
    snap2 = Table.for_path(str(tmp_path), eng).latest_snapshot()
    prev = read_last_checkpoint(eng.fs, log)
    r0 = PARTS_REUSED.value
    write_checkpoint(eng, snap2, prev_info=prev)
    assert PARTS_REUSED.value == r0
    _drop_commits(log, 11)
    assert _digest(tmp_path, eng)[1] == 55


# ---------------------------------------------- torn writes / abort path


def _chaos_engine(seed, **rates):
    store = ChaosStore(InMemoryLogStore(), ChaosSchedule(seed, **rates),
                       sleep=lambda s: None)
    return HostEngine(store_resolver=lambda path: store), store


def _build_mem_log(store, table_path, ncommits, per=5):
    log = f"{table_path}/_delta_log"
    store.enabled = False
    data = "\n".join(json.dumps(a) for a in [PROTOCOL, METADATA]) + "\n"
    store.write(f"{log}/{0:020d}.json", data.encode())
    for v in range(1, ncommits + 1):
        data = "\n".join(
            json.dumps(a) for a in _commit_actions(v, per)) + "\n"
        store.write(f"{log}/{v:020d}.json", data.encode())
    store.enabled = True
    return log


def test_torn_multipart_aborts_cleans_up_and_keeps_table_readable(tmp_path):
    """A torn part upload fails the whole checkpoint: orphans are
    deleted, `_last_checkpoint` is never written, the aborted-writes
    counter moves, and the table still loads from the commit log."""
    eng, store = _chaos_engine(seed=3, error_rate=0.0, torn_write_rate=1.0)
    table_path = "mem://ckpt-torn"
    log = _build_mem_log(store, table_path, 10)
    settings.checkpoint_part_size = 12

    snap = Table.for_path(table_path, eng).latest_snapshot()
    a0 = ABORTED.value
    with pytest.raises(Exception) as exc_info:
        write_checkpoint(eng, snap)
    assert isinstance(exc_info.value,
                      (ckpt_pipeline.CheckpointWriteError, ChaosError))
    assert ABORTED.value == a0 + 1
    assert store.fault_counts.get("torn_write", 0) >= 1

    store.enabled = False
    assert read_last_checkpoint(eng.fs, log) is None
    leftovers = [s.path for s in store.list_from(f"{log}/")
                 if ".checkpoint" in s.path]
    assert leftovers == []  # every torn/created part was deleted
    clear_parse_cache()
    snap2 = Table.for_path(table_path, eng).latest_snapshot()
    assert snap2.version == 10 and snap2.state.num_files == 50


def test_torn_v2_top_level_cleans_fresh_sidecars_only(tmp_path):
    """When the V2 top-level write tears, this attempt's fresh sidecars
    are deleted but sidecars re-referenced from the previous checkpoint
    survive (they belong to the still-active checkpoint)."""
    eng, store = _chaos_engine(seed=5, error_rate=0.0, torn_write_rate=0.0)
    table_path = "mem://ckpt-v2-torn"
    log = _build_mem_log(store, table_path, 10)
    settings.checkpoint_part_size = 12

    snap = Table.for_path(table_path, eng).latest_snapshot()
    store.enabled = False
    write_checkpoint(eng, snap, policy="v2")
    prev = read_last_checkpoint(eng.fs, log)
    sidecars_before = {s.path for s in store.list_from(f"{log}/_sidecars/")}
    hint_before = store.read(f"{log}/_last_checkpoint")

    store.enabled = False
    _ = [store.write(f"{log}/{v:020d}.json",
                     ("\n".join(json.dumps(a)
                                for a in _commit_actions(v)) + "\n").encode())
         for v in (11, 12)]
    clear_parse_cache()
    snap2 = Table.for_path(table_path, eng).latest_snapshot()
    # tear only top-level checkpoint files, not sidecars
    store.schedule.torn_write_rate = 1.0
    store.torn_pred = lambda path: "_sidecars" not in path
    store.enabled = True
    a0 = ABORTED.value
    with pytest.raises(Exception):
        write_checkpoint(eng, snap2, policy="v2", prev_info=prev)
    assert ABORTED.value == a0 + 1

    store.enabled = False
    sidecars_after = {s.path for s in store.list_from(f"{log}/_sidecars/")}
    assert sidecars_before <= sidecars_after  # reused sidecars survived
    assert len(sidecars_after) == len(sidecars_before)  # fresh one deleted
    tops = [s.path for s in store.list_from(f"{log}/")
            if ".checkpoint" in s.path and "_sidecars" not in s.path]
    # the version-10 checkpoint survives; the torn version-12 top-level
    # (and any retry half-file) was deleted
    assert tops and all(f"{10:020d}.checkpoint" in p for p in tops)
    assert store.read(f"{log}/_last_checkpoint") == hint_before


# ------------------------------------------------------ profitability gate


def test_gate_stands_down_locally_engages_remote(tmp_path, monkeypatch):
    monkeypatch.delenv("DELTA_TPU_CKPT_PIPELINE", raising=False)
    local_eng = HostEngine()
    log = _build_local_log(tmp_path, 3)
    # local store: the pool fan-out already saturates the disk
    assert ckpt_pipeline.profitable(local_eng, log, 5) is False
    # single artifact: nothing to overlap, even remotely
    mem_eng, _store = _chaos_engine(seed=1, error_rate=0.0)
    assert ckpt_pipeline.profitable(mem_eng, "mem://t/_delta_log", 1) is False
    # non-local store: upload latency is what the pipeline hides
    assert ckpt_pipeline.profitable(mem_eng, "mem://t/_delta_log", 5) is True
    # off kills it everywhere; force engages it everywhere
    monkeypatch.setenv("DELTA_TPU_CKPT_PIPELINE", "off")
    assert ckpt_pipeline.profitable(mem_eng, "mem://t/_delta_log", 5) is False
    monkeypatch.setenv("DELTA_TPU_CKPT_PIPELINE", "force")
    assert ckpt_pipeline.profitable(local_eng, log, 1) is True


def test_forced_pipeline_parity_and_stall_accounting(tmp_path, monkeypatch):
    """Forcing the pipeline on a local store must not change the
    resulting state, and the stall counters must account the overlap."""
    log = _build_local_log(tmp_path, 10)
    settings.checkpoint_part_size = 12
    eng = HostEngine()
    live = _digest(tmp_path, eng)

    monkeypatch.setenv("DELTA_TPU_CKPT_PIPELINE", "force")
    s0 = obs.counter("checkpoint.upload_stall_ns").value
    snap = Table.for_path(str(tmp_path), eng).latest_snapshot()
    write_checkpoint(eng, snap)
    assert obs.counter("checkpoint.upload_stall_ns").value > s0

    _drop_commits(log, 10)
    assert _digest(tmp_path, eng) == live


# --------------------------------------------------- device kernel parity


def _random_lanes(rng, n, n_parts):
    lanes, valids = [], []
    for _ in range(3):
        lanes.append(rng.integers(-2**40, 2**40, size=n))
        valids.append(rng.random(n) > 0.2)
    codes = rng.integers(0, 5, size=n)
    lanes.append(codes)
    valids.append(np.ones(n, bool))
    part_of = rng.integers(0, n_parts, size=n).astype(np.int32)
    return lanes, valids, part_of


@pytest.mark.parametrize("n,n_parts", [(0, 1), (7, 1), (1000, 9)])
def test_stats_block_host_device_parity(n, n_parts):
    from delta_tpu.ops import stats as ckstats

    rng = np.random.default_rng(n + n_parts)
    lanes, valids, part_of = _random_lanes(rng, n, n_parts)
    host = ckstats.host_stats_block(lanes, valids, part_of, n_parts, 5)
    dev = ckstats.checkpoint_stats_block(lanes, valids, part_of, n_parts, 5)
    assert host.dtype == dev.dtype == np.int64
    assert np.array_equal(host, dev)


def test_dv_device_pack_byte_parity(monkeypatch):
    from delta_tpu.dv.roaring import RoaringBitmapArray

    rng = np.random.default_rng(11)
    vals = np.unique(np.concatenate([
        rng.choice(65536, size=30000, replace=False),            # bitmap
        65536 + rng.choice(65536, size=500, replace=False),      # array
        2 * 65536 + rng.choice(65536, size=60000, replace=False),  # bitmap
        (1 << 32) + rng.choice(65536, size=5000, replace=False),  # bitmap
    ]).astype(np.uint64))
    bm = RoaringBitmapArray(values=vals)
    monkeypatch.delenv("DELTA_TPU_DEVICE_DV_PACK", raising=False)
    host = bm.serialize_delta()
    monkeypatch.setenv("DELTA_TPU_DEVICE_DV_PACK", "1")
    dev = bm.serialize_delta()
    assert host == dev
    rt = RoaringBitmapArray.deserialize_delta(dev)
    assert np.array_equal(rt.values, vals)

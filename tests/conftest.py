"""Test bootstrap: force an 8-device virtual CPU mesh.

Tests exercise the multi-chip sharding paths on virtual CPU devices (the
driver separately validates multi-chip via __graft_entry__.dryrun_multichip);
bench.py runs unforced on the real TPU chip.

Note: some environments (axon) import and configure jax at interpreter
startup via sitecustomize — env vars alone are too late, so we override
`jax_platforms` through jax.config and set XLA_FLAGS before the first
backend initialization (backends init lazily at first use).
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pyarrow as pa  # noqa: E402
import pytest  # noqa: E402


def pytest_report_header(config):
    return f"jax devices: {jax.devices()}"


@pytest.fixture(autouse=True)
def _fast_resilience(monkeypatch):
    """Keep retry backoff near-instant and isolate breaker state.

    Production defaults sleep up to seconds between attempts; a suite
    full of injected persistent faults would crawl. Per-endpoint
    breakers are process-wide, so one test's fault barrage must not
    fast-fail the next test's IO."""
    from delta_tpu import resilience

    monkeypatch.setenv("DELTA_TPU_RETRY_BASE_MS", "1")
    monkeypatch.setenv("DELTA_TPU_RETRY_CAP_MS", "5")
    monkeypatch.setenv("DELTA_TPU_RETRY_DEADLINE_S", "10")
    resilience.reset()
    yield
    resilience.reset()


@pytest.fixture
def tmp_table_path(tmp_path):
    return str(tmp_path / "table")


@pytest.fixture
def host_engine():
    from delta_tpu.engine.host import HostEngine

    return HostEngine()


@pytest.fixture
def tpu_engine():
    from delta_tpu.engine.tpu import TpuEngine

    return TpuEngine()


@pytest.fixture
def sample_data():
    rng = np.random.default_rng(7)
    n = 1000
    return pa.table(
        {
            "id": pa.array(np.arange(n, dtype=np.int64)),
            "value": pa.array(rng.normal(size=n)),
            "category": pa.array([f"cat{i % 5}" for i in range(n)]),
            "date": pa.array([f"2024-01-{(i % 28) + 1:02d}" for i in range(n)]),
        }
    )


@pytest.fixture
def coordinated_path(tmp_table_path):
    """A coordinated-commits table backed by the in-memory coordinator."""
    import numpy as np
    import pyarrow as pa

    import delta_tpu.api as dta
    from delta_tpu.coordinatedcommits import (
        COORDINATOR_NAME_KEY,
        InMemoryCommitCoordinator,
        register_coordinator,
    )

    register_coordinator("test-coord", InMemoryCommitCoordinator(batch_size=3))
    dta.write_table(
        tmp_table_path,
        pa.table({"id": pa.array(np.arange(5, dtype=np.int64))}),
        properties={COORDINATOR_NAME_KEY: "test-coord"},
    )
    return tmp_table_path

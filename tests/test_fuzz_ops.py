"""Differential operation fuzz: a random sequence of table operations
(append / delete / update / optimize / checkpoint) executed once, then
the resulting `_delta_log` replayed independently by BOTH engines —
states must agree bit-for-bit, and reads must match a Python-dict model
of the table contents. A deterministic time-travel check and a restore
run once at the end of each sequence.

This is the end-to-end analogue of the replay-kernel fuzz: it exercises
commit writing, checkpoints mid-history, DV deletes, CDC writes, and
time travel against the same log.
"""

import numpy as np
import pyarrow as pa
import pytest

import delta_tpu.api as dta
from delta_tpu.commands.dml import delete, update
from delta_tpu.commands.restore import restore
from delta_tpu.engine.host import HostEngine
from delta_tpu.engine.tpu import TpuEngine
from delta_tpu.expressions import col, lit
from delta_tpu.table import Table


def _state_fingerprint(snap):
    t = snap.state.add_files_table
    rows = sorted(zip(
        t.column("path").to_pylist(),
        t.column("dv_id").to_pylist(),
        t.column("size").to_pylist(),
    ))
    return snap.version, snap.num_files, rows


@pytest.mark.parametrize("seed,variant", [
    (11, 0),   # baseline (CDF only)
    (23, 1),   # deletion vectors
    (47, 2),   # column mapping + deletion vectors
    (61, 3),   # v2 checkpoints
])
def test_random_op_sequence_engines_agree(tmp_table_path, seed, variant):
    rng = np.random.default_rng(seed)
    props = {"delta.enableChangeDataFeed": "true"}
    if variant == 1:
        props["delta.enableDeletionVectors"] = "true"
    elif variant == 2:
        props["delta.columnMapping.mode"] = "name"
        props["delta.enableDeletionVectors"] = "true"
    elif variant == 3:
        props["delta.checkpointPolicy"] = "v2"

    # model: id -> value
    model = {}

    def batch(ids, vals):
        return pa.table({"id": pa.array(ids, pa.int64()),
                         "v": pa.array(vals, pa.int64())})

    next_id = 0

    def do_append():
        nonlocal next_id
        n = int(rng.integers(1, 40))
        ids = list(range(next_id, next_id + n))
        vals = [int(rng.integers(0, 1000)) for _ in ids]
        next_id += n
        dta.write_table(tmp_table_path, batch(ids, vals), mode="append")
        model.update(dict(zip(ids, vals)))

    def do_delete():
        if not model:
            return
        cut = int(rng.integers(0, next_id))
        delete(Table.for_path(tmp_table_path), col("id") < lit(cut))
        for k in [k for k in model if k < cut]:
            del model[k]

    def do_update():
        if not model:
            return
        cut = int(rng.integers(0, next_id))
        update(Table.for_path(tmp_table_path), {"v": lit(7)},
               col("id") >= lit(cut))
        for k in [k for k in model if k >= cut]:
            model[k] = 7

    def do_optimize():
        Table.for_path(tmp_table_path).optimize().execute_compaction()

    def do_checkpoint():
        Table.for_path(tmp_table_path).checkpoint()

    def do_merge():
        nonlocal next_id
        from delta_tpu.commands.merge import merge as _merge

        n_upd = int(rng.integers(1, 6))
        upd_ids = [int(rng.integers(0, next_id)) for _ in range(n_upd)]
        new_ids = [next_id, next_id + 1]
        next_id += 2
        ids = sorted(set(upd_ids)) + new_ids
        vals = [int(rng.integers(0, 1000)) for _ in ids]
        (_merge(Table.for_path(tmp_table_path), batch(ids, vals),
                on=col("target.id") == col("source.id"))
         .when_matched_update_all()
         .when_not_matched_insert_all()
         .execute())
        # every source row lands: matched -> updated, unmatched
        # (including previously-deleted ids) -> inserted
        for i, v in zip(ids, vals):
            model[i] = v

    ops = [do_append, do_append, do_delete, do_update, do_optimize,
           do_checkpoint, do_merge]
    dta.write_table(tmp_table_path, batch([0], [0]), properties=props)
    model[0] = 0
    next_id = 1
    for _ in range(30):
        ops[int(rng.integers(0, len(ops)))]()

    host_snap = Table.for_path(tmp_table_path, HostEngine()).latest_snapshot()
    tpu_snap = Table.for_path(tmp_table_path, TpuEngine()).latest_snapshot()
    assert _state_fingerprint(host_snap) == _state_fingerprint(tpu_snap)

    out = dta.read_table(tmp_table_path, engine=TpuEngine())
    got = dict(zip(out.column("id").to_pylist(), out.column("v").to_pylist()))
    assert got == model

    # time travel to a mid-history version agrees across engines too
    mid = host_snap.version // 2
    h_mid = Table.for_path(tmp_table_path, HostEngine()).snapshot_at(mid)
    t_mid = Table.for_path(tmp_table_path, TpuEngine()).snapshot_at(mid)
    assert _state_fingerprint(h_mid) == _state_fingerprint(t_mid)

    # restore to mid, verify reads still consistent on both engines
    restore(Table.for_path(tmp_table_path), version=mid)
    h = dta.read_table(tmp_table_path, engine=HostEngine())
    t = dta.read_table(tmp_table_path, engine=TpuEngine())
    assert sorted(h.column("id").to_pylist()) == sorted(t.column("id").to_pylist())

import numpy as np
import pytest

from delta_tpu.dv.roaring import RoaringBitmapArray
from delta_tpu.dv.descriptor import (
    decode_uuid_base85,
    encode_uuid_base85,
    inline_descriptor,
    load_deletion_vector,
    write_deletion_vector_file,
)


@pytest.mark.parametrize(
    "values",
    [
        [],
        [0],
        [0, 1, 2, 3],
        [2, 5, 7, 8, 1000, 65535, 65536, 65537],
        list(range(5000)),                      # bitmap container
        [2**32 - 1, 2**32, 2**32 + 5, 2**40],   # multiple buckets
        list(range(100000, 200000, 3)),
    ],
)
def test_roaring_roundtrip(values):
    bm = RoaringBitmapArray(np.array(values, dtype=np.uint64))
    data = bm.serialize_delta()
    back = RoaringBitmapArray.deserialize_delta(data)
    assert back == bm
    assert back.cardinality == len(set(values))


def test_roaring_fuzz():
    rng = np.random.default_rng(42)
    for _ in range(10):
        n = rng.integers(1, 20000)
        vals = rng.integers(0, 2**40, n).astype(np.uint64)
        bm = RoaringBitmapArray(vals)
        back = RoaringBitmapArray.deserialize_delta(bm.serialize_delta())
        assert back == bm


def test_roaring_run_container_decode():
    """Hand-build a WITH_RUN serialization and decode it."""
    import struct

    # one run container: key 0, values 10..19
    n = 1
    cookie = ((n - 1) << 16) | 12347
    buf = struct.pack("<I", cookie)
    buf += bytes([0b1])            # run flag bitset
    buf += struct.pack("<HH", 0, 9)  # key 0, card-1 = 9
    # n < 4 -> no offsets
    buf += struct.pack("<H", 1)      # numRuns
    buf += struct.pack("<HH", 10, 9)  # start 10, length-1 9
    bitmap32 = struct.pack("<q", 1) + struct.pack("<I", 0) + buf
    full = struct.pack("<i", 1681511377) + bitmap32
    bm = RoaringBitmapArray.deserialize_delta(full)
    assert bm.values.tolist() == list(range(10, 20))


def test_to_mask_and_contains():
    bm = RoaringBitmapArray(np.array([1, 5, 9], dtype=np.uint64))
    mask = bm.to_mask(8)
    assert mask.tolist() == [False, True, False, False, False, True, False, False]
    assert bm.contains(np.array([1, 2, 9])).tolist() == [True, False, True]


def test_uuid_base85_roundtrip():
    import uuid

    u = uuid.uuid4()
    enc = encode_uuid_base85(u)
    assert len(enc) == 20
    assert decode_uuid_base85(enc) == u


def test_dv_file_roundtrip(tmp_path):
    from delta_tpu.engine.host import HostEngine

    engine = HostEngine()
    table_path = str(tmp_path)
    bm1 = RoaringBitmapArray(np.array([1, 2, 3], dtype=np.uint64))
    bm2 = RoaringBitmapArray(np.array([10, 2**33], dtype=np.uint64))
    descs = write_deletion_vector_file(engine, table_path, [bm1, bm2])
    assert len(descs) == 2
    assert descs[0].cardinality == 3
    v1 = load_deletion_vector(engine, table_path, descs[0].to_dict())
    v2 = load_deletion_vector(engine, table_path, descs[1].to_dict())
    assert v1.tolist() == [1, 2, 3]
    assert v2.tolist() == [10, 2**33]


def test_inline_dv_roundtrip():
    from delta_tpu.engine.host import HostEngine

    bm = RoaringBitmapArray(np.array([7, 8, 1000], dtype=np.uint64))
    desc = inline_descriptor(bm)
    assert desc.storageType == "i"
    vals = load_deletion_vector(HostEngine(), "/nope", desc.to_dict())
    assert vals.tolist() == [7, 8, 1000]

"""HBM resident ledger (`obs.hbm`): handle lifecycle, leak detection
via owner finalizers, the strict reconciliation audit over real
snapshot loads, serve-cache eviction accounting, and the `delta-hbm`
CLI round-trip.

Everything runs on CPU (the conftest mesh emulates 8 devices); the
integration tests drive the real resident replay / stats-index /
checkpoint-handoff owners through their production registration sites
and assert the ledger reconciles byte-exactly — zero drift, zero
leaks — across load, advance, and eviction."""

import gc
import json
import time

import jax.numpy as jnp
import numpy as np
import pytest

from delta_tpu import obs
from delta_tpu.obs import hbm
from delta_tpu.tools import hbm_cli


@pytest.fixture(autouse=True)
def _clean_hbm_obs():
    """Every test starts and ends with an empty ledger and the mode
    re-read from the (test-runner) env — and, critically, with stale
    finalizers from earlier tests' owners detached so their GC can't
    report leaks into this test's epoch."""
    obs.reset_hbm_obs()
    obs.set_hbm_obs_mode("on")
    yield
    obs.set_hbm_obs_mode(None)
    obs.reset_hbm_obs()


def _counter_value(name):
    return obs.counter(name).value


class _Owner:
    """A minimal weakref-able artifact owner."""


# ------------------------------------------------------- lifecycle ----------


def test_register_touch_grow_release_lifecycle():
    arr = jnp.arange(256, dtype=jnp.int32)
    owner = _Owner()
    with hbm.table_scope("/tables/alpha"):
        h = hbm.register(owner, kind=hbm.KIND_REPLAY_KEYS, version=7,
                         arrays=(arr,), rebuild_cost_class="expensive")
    assert h.nbytes == arr.nbytes
    assert h.table_path == "/tables/alpha"     # ambient scope resolved
    assert h.version == 7
    led = hbm.ledger()
    assert led.total_bytes() == arr.nbytes
    assert led.artifact_count() == 1
    assert led.kind_bytes(hbm.KIND_REPLAY_KEYS) == arr.nbytes

    before = h.last_access
    time.sleep(0.002)
    h.touch()
    assert h.last_access > before

    grown = jnp.arange(1024, dtype=jnp.int32)
    h.grow(arrays=(grown,))
    assert h.nbytes == grown.nbytes
    assert led.total_bytes() == grown.nbytes
    assert led.peak_bytes() == grown.nbytes

    h.release()
    h.release()                                # idempotent
    assert led.total_bytes() == 0
    assert led.artifact_count() == 0
    assert led.peak_bytes() == grown.nbytes    # peak survives release
    del owner


def test_explicit_table_path_outranks_scope():
    owner = _Owner()
    with hbm.table_scope("/tables/ambient"):
        h = hbm.register(owner, kind=hbm.KIND_STATS_INDEX,
                         table_path="/tables/explicit", nbytes=64)
    assert h.table_path == "/tables/explicit"
    h.release()


def test_rollup_both_dimensions():
    owners = [_Owner() for _ in range(3)]
    hbm.register(owners[0], kind=hbm.KIND_REPLAY_KEYS,
                 table_path="/t/a", nbytes=100)
    hbm.register(owners[1], kind=hbm.KIND_STATS_INDEX,
                 table_path="/t/a", nbytes=10)
    hbm.register(owners[2], kind=hbm.KIND_REPLAY_KEYS,
                 table_path="/t/b", nbytes=1000)
    by_table = hbm.rollup(by="table")
    assert by_table["/t/a"] == {
        "nbytes": 110, "artifacts": 2,
        "by_kind": {hbm.KIND_REPLAY_KEYS: 100, hbm.KIND_STATS_INDEX: 10}}
    by_kind = hbm.rollup(by="kind")
    assert by_kind[hbm.KIND_REPLAY_KEYS]["nbytes"] == 1100
    assert by_kind[hbm.KIND_REPLAY_KEYS]["by_table"] == {
        "/t/a": 100, "/t/b": 1000}
    with pytest.raises(ValueError):
        hbm.rollup(by="color")
    del owners


def test_gauges_are_ledger_derived():
    owner = _Owner()
    hbm.register(owner, kind=hbm.KIND_REPLAY_KEYS, nbytes=2048)
    assert obs.gauge("hbm.resident_bytes").read() == 2048
    assert obs.gauge("hbm.resident_artifacts").read() == 1
    assert obs.gauge("hbm.resident_bytes_peak").read() == 2048
    # the subsumed pre-ledger names stay live, per-kind
    assert obs.gauge("replay.resident_hbm_bytes").read() == 2048
    assert obs.gauge("scan.stats_index_hbm_bytes").read() == 0


# ---------------------------------------------------- disabled path ---------


def test_off_mode_returns_shared_noop_handle():
    obs.set_hbm_obs_mode("off")
    a = hbm.register(_Owner(), kind=hbm.KIND_REPLAY_KEYS, nbytes=999)
    b = hbm.register(None, kind=hbm.KIND_STATS_INDEX)
    assert a is b is hbm.noop_handle()   # process-wide singleton
    a.touch()
    a.grow(nbytes=123)
    a.release()                          # all no-ops, all safe
    assert hbm.ledger().total_bytes() == 0
    assert hbm.ledger().artifact_count() == 0


def test_off_mode_register_overhead_is_negligible():
    """The off-mode register must cost nanoseconds, not microseconds.
    Gate at a generous 5us/call so a loaded CI box cannot flake; the
    bench asserts the real <2% bound (hbm_accounting_overhead_pct)."""
    obs.set_hbm_obs_mode("off")
    n = 20_000
    t0 = time.perf_counter_ns()
    for _ in range(n):
        h = hbm.register(None, kind=hbm.KIND_REPLAY_KEYS, nbytes=8)
        h.touch()
        h.release()
    per_call_ns = (time.perf_counter_ns() - t0) / n
    assert per_call_ns < 5_000


def test_bad_mode_string_rejected():
    with pytest.raises(ValueError):
        obs.set_hbm_obs_mode("loud")


# ------------------------------------------------------ leak tracing --------


def test_owner_gc_without_release_counts_leak():
    leaks0 = _counter_value("hbm.resident_leaks")
    owner = _Owner()
    hbm.register(owner, kind=hbm.KIND_REPLAY_KEYS,
                 table_path="/t/leaky", nbytes=4096)
    assert hbm.ledger().total_bytes() == 4096
    del owner
    gc.collect()
    assert _counter_value("hbm.resident_leaks") == leaks0 + 1
    recs = hbm.leak_records()
    assert len(recs) == 1
    assert recs[0]["table_path"] == "/t/leaky"
    assert recs[0]["kind"] == hbm.KIND_REPLAY_KEYS
    assert recs[0]["nbytes"] == 4096
    # the leak auto-deregisters: gauges must not keep counting a
    # buffer that died with its owner
    assert hbm.ledger().total_bytes() == 0
    assert hbm.ledger().artifact_count() == 0


def test_release_detaches_finalizer_no_phantom_leak():
    leaks0 = _counter_value("hbm.resident_leaks")
    owner = _Owner()
    h = hbm.register(owner, kind=hbm.KIND_STATS_INDEX, nbytes=64)
    h.release()
    del owner
    gc.collect()
    assert _counter_value("hbm.resident_leaks") == leaks0


def test_leak_fails_audit_and_strict_raises():
    owner = _Owner()
    hbm.register(owner, kind=hbm.KIND_CKPT_HANDOFF,
                 table_path="/t/leaky", nbytes=128)
    del owner
    gc.collect()
    result = hbm.audit()
    assert not result["ok"] and result["leaks"]
    obs.set_hbm_obs_mode("strict")
    with pytest.raises(RuntimeError, match="leaked"):
        hbm.audit()


def test_strict_audit_detects_unrecorded_grow_as_drift():
    arr = jnp.arange(64, dtype=jnp.int32)
    owner = _Owner()
    h = hbm.register(owner, kind=hbm.KIND_REPLAY_KEYS, arrays=(arr,))
    # lie about the size: the registered figure no longer matches the
    # live array — that's drift, byte-exactly
    h.grow(nbytes=h.nbytes + 8)
    obs.set_hbm_obs_mode("strict")
    with pytest.raises(RuntimeError, match="unrecorded grow"):
        hbm.audit()
    h.release()
    del owner


# ------------------------------------- reconciliation over real loads -------


def _tpu_table(tmp_path, n_commits, files_per_commit=20):
    from delta_tpu.engine.tpu import TpuEngine
    from delta_tpu.models.actions import AddFile, RemoveFile
    from delta_tpu.models.schema import INTEGER, StructField, StructType
    from delta_tpu.table import Table

    eng = TpuEngine(replay_shards=8)
    t = Table.for_path(str(tmp_path), eng)
    t.create_transaction_builder().with_schema(
        StructType([StructField("x", INTEGER)])).build().commit()
    for i in range(n_commits):
        txn = t.start_transaction()
        for j in range(files_per_commit):
            txn.add_file(AddFile(
                path=f"p{i}_{j}.parquet", partitionValues={}, size=100 + j,
                modificationTime=1000 + i, dataChange=True,
                stats=json.dumps({"numRecords": 10 * j,
                                  "minValues": {"x": j},
                                  "maxValues": {"x": j + 100}})))
        if i > 0:
            txn.remove_file(RemoveFile(
                path=f"p{i - 1}_0.parquet", deletionTimestamp=2000 + i,
                dataChange=True))
        txn.commit()
    return t


def test_strict_reconciliation_over_sharded_load_and_advance(tmp_path):
    """The acceptance cycle: a real sharded load registers the resident
    replay key lane under the right table, the audit reconciles
    byte-exactly against jax.live_arrays(), an incremental advance
    grows the entry in place (still byte-exact), and releasing leaves
    the ledger empty — all under strict, which would raise on any
    drift or leak."""
    from delta_tpu.models.actions import AddFile
    from delta_tpu.parallel.resident import release_snapshot_resident

    obs.set_hbm_obs_mode("strict")
    t = _tpu_table(tmp_path, 8)
    snap = t.latest_snapshot()
    _ = snap.state.live_mask  # force replay
    res = snap._state.resident
    assert res is not None, "sharded load did not establish residency"

    led = hbm.ledger()
    assert led.artifact_count() == 1
    assert led.kind_bytes(hbm.KIND_REPLAY_KEYS) == res.key_sh.nbytes
    [rec] = hbm.residents()
    assert rec["table_path"] == str(tmp_path)   # table_scope attribution
    assert rec["kind"] == hbm.KIND_REPLAY_KEYS
    assert rec["rebuild_cost_class"] == "expensive"
    result = hbm.audit()                        # strict: raises on drift
    assert result["ok"]
    assert result["verified_bytes"] == result["ledger_bytes"] \
        == res.key_sh.nbytes

    # advance: the donated in-place append swaps the device buffer;
    # grow() must re-point the audit weakrefs and re-account the bytes
    txn = t.start_transaction()
    for j in range(20):
        txn.add_file(AddFile(
            path=f"inc_{j}.parquet", partitionValues={}, size=50,
            modificationTime=5000, dataChange=True))
    txn.commit()
    snap2 = t.update()
    assert snap2._state.resident is res
    assert led.artifact_count() == 1            # moved, not re-registered
    result = hbm.audit()
    assert result["ok"]
    assert result["verified_bytes"] == result["ledger_bytes"] \
        == res.key_sh.nbytes

    release_snapshot_resident(snap2)
    assert led.total_bytes() == 0
    assert led.artifact_count() == 0
    assert hbm.audit()["ok"]

    del snap, snap2, res
    gc.collect()
    hbm.audit()                                 # strict: no leaks either


def test_stats_index_lanes_register_with_table_attribution(tmp_path):
    from delta_tpu.stats.device_index import snapshot_stats_index

    obs.set_hbm_obs_mode("strict")
    t = _tpu_table(tmp_path, 3)
    snap = t.latest_snapshot()
    state = snap.state
    idx = snapshot_stats_index(state, state.add_files_table)
    assert idx is not None and idx.has_lanes
    lanes = idx.device_lanes()
    assert lanes[0] is not None

    led = hbm.ledger()
    nbytes = led.kind_bytes(hbm.KIND_STATS_INDEX)
    assert nbytes > 0
    recs = [r for r in hbm.residents()
            if r["kind"] == hbm.KIND_STATS_INDEX]
    assert len(recs) == 1
    assert recs[0]["table_path"] == str(tmp_path)
    assert recs[0]["version"] == snap.version
    assert recs[0]["rebuild_cost_class"] == "cheap"
    assert hbm.audit()["ok"]

    touches0 = led.touches
    idx.device_lanes()                          # read path touches
    assert led.touches > touches0

    idx.release()
    assert led.kind_bytes(hbm.KIND_STATS_INDEX) == 0
    assert hbm.audit()["ok"]


def test_handoff_part_keys_release_helper():
    from delta_tpu.ops.page_decode import PartKeys, release_part_keys

    codes = jnp.arange(128, dtype=jnp.uint32)
    keys = PartKeys(codes=codes, n_add=4, n_rem=0, n_bad=0,
                    uniq=[], n_rows=4)
    keys.hbm = hbm.register(keys, kind=hbm.KIND_CKPT_HANDOFF,
                            table_path="/t/ckpt", arrays=(codes,),
                            rebuild_cost_class="cheap")
    assert hbm.ledger().kind_bytes(hbm.KIND_CKPT_HANDOFF) == codes.nbytes
    release_part_keys([keys])
    assert keys.hbm is None
    assert hbm.ledger().kind_bytes(hbm.KIND_CKPT_HANDOFF) == 0
    release_part_keys([keys])                   # idempotent on None


def test_serve_cache_eviction_releases_everything(tmp_path):
    """Evicting a cached table must deregister every ledger-accounted
    artifact it owned (replay key lane AND stats-index lane); the
    strict audit proves nothing leaked and nothing drifted."""
    from delta_tpu.engine.tpu import TpuEngine
    from delta_tpu.serve.cache import SnapshotCache
    from delta_tpu.serve.config import ServeConfig
    from delta_tpu.stats.device_index import snapshot_stats_index

    obs.set_hbm_obs_mode("strict")
    t1 = _tpu_table(tmp_path / "t1", 6)
    t2 = _tpu_table(tmp_path / "t2", 6)
    del t1, t2
    # the builder tables' own commit-path residents are not under test;
    # start this epoch with an empty ledger so every entry below is
    # cache-owned
    obs.reset_hbm_obs()
    eng = TpuEngine(replay_shards=8)
    cache = SnapshotCache(eng, ServeConfig(cache_tables=1,
                                           refresh_ms=60_000.0))

    snap, meta = cache.snapshot_for(str(tmp_path / "t1"))
    assert meta == {}
    _ = snap.state.live_mask
    assert snap._state.resident is not None
    idx = snapshot_stats_index(snap.state, snap.state.add_files_table)
    assert idx is not None and idx.device_lanes()[0] is not None

    led = hbm.ledger()
    t1_path = str(tmp_path / "t1")
    assert {r["table_path"] for r in hbm.residents()} == {t1_path}
    assert led.artifact_count() == 2
    assert hbm.audit()["ok"]

    # a warm hit touches the resident artifacts (recency accounting)
    touches0 = led.touches
    cache.snapshot_for(t1_path)
    assert led.touches > touches0

    # capacity 1: loading the second table evicts the first, and the
    # eviction releases both of its device lanes through the ledger
    snap2, _ = cache.snapshot_for(str(tmp_path / "t2"))
    _ = snap2.state.live_mask
    assert all(r["table_path"] != t1_path for r in hbm.residents())
    result = hbm.audit()
    assert result["ok"]

    del snap, idx
    gc.collect()
    hbm.audit()                                 # still zero leaks


# ------------------------------------------------- health + CLI -------------


def test_health_summary_shape():
    owner = _Owner()
    hbm.register(owner, kind=hbm.KIND_REPLAY_KEYS,
                 table_path="/t/a", nbytes=512)
    s = hbm.health_summary()
    assert s["resident_bytes"] == 512
    assert s["resident_artifacts"] == 1
    assert s["peak_bytes"] == 512
    assert s["by_kind"] == {hbm.KIND_REPLAY_KEYS: 512}
    assert isinstance(s["leaks"], int)
    del owner


def test_cli_rollup_roundtrips_from_jsonl(tmp_path):
    owners = [_Owner() for _ in range(3)]
    hbm.register(owners[0], kind=hbm.KIND_REPLAY_KEYS,
                 table_path="/t/a", version=3, nbytes=4096)
    hbm.register(owners[1], kind=hbm.KIND_STATS_INDEX,
                 table_path="/t/a", version=3, nbytes=256)
    hbm.register(owners[2], kind=hbm.KIND_REPLAY_KEYS,
                 table_path="/t/b", version=9, nbytes=8192)
    dump = tmp_path / "ledger.jsonl"
    assert hbm.dump_ledger(str(dump)) == 3

    residents, leaks = hbm_cli.load_ledger_dump(str(dump))
    assert len(residents) == 3 and not leaks
    # the dump-side rollup must match the live ledger record-for-record
    assert hbm_cli.rollup_records(residents, by="table") \
        == hbm.rollup(by="table")
    assert hbm_cli.rollup_records(residents, by="kind") \
        == hbm.rollup(by="kind")
    del owners


def test_cli_views_and_exit_codes(tmp_path, capsys):
    owner = _Owner()
    hbm.register(owner, kind=hbm.KIND_REPLAY_KEYS,
                 table_path="/t/a", nbytes=4096)
    leaker = _Owner()
    hbm.register(leaker, kind=hbm.KIND_STATS_INDEX,
                 table_path="/t/gone", nbytes=64)
    del leaker
    gc.collect()
    dump = tmp_path / "ledger.jsonl"
    hbm.dump_ledger(str(dump))

    assert hbm_cli.main([str(dump)]) == 0
    out = capsys.readouterr().out
    assert "/t/a" in out and "replay-keys" in out

    assert hbm_cli.main([str(dump), "--top", "5", "--json"]) == 0
    top = json.loads(capsys.readouterr().out)
    assert top[0]["nbytes"] == 4096

    # leaks present -> report + nonzero exit (the CI grep signal)
    assert hbm_cli.main([str(dump), "--leaks"]) == 1
    out = capsys.readouterr().out
    assert "LEAK" in out and "/t/gone" in out

    assert hbm_cli.main([str(tmp_path / "missing.jsonl")]) == 2


def test_serve_health_carries_hbm_section():
    """The serve health() payload exposes the ledger summary (no accept
    thread needed — construct the server and call the handler)."""
    from delta_tpu.serve.server import DeltaServeServer

    owner = _Owner()
    hbm.register(owner, kind=hbm.KIND_REPLAY_KEYS,
                 table_path="/t/a", nbytes=1024)
    srv = DeltaServeServer("127.0.0.1", 0)
    try:
        health = srv.health()
    finally:
        srv._listener.close()
    assert health["hbm"]["resident_bytes"] == 1024
    assert health["hbm"]["by_kind"] == {hbm.KIND_REPLAY_KEYS: 1024}
    assert health["hbm"]["resident_artifacts"] == 1
    del owner

"""Telemetry-plane coverage: Prometheus exposition (render/parse,
fixed buckets, catalog zero-fill, gauge callbacks), the flight
recorder ring, SLO burn-rate gates under a fake clock, distributed
trace propagation across BOTH connect servers (including the hedged
losing-attempt branch shape), the inline metrics scrape, the
delta-metrics CLI, and Chrome-export process grouping."""

from __future__ import annotations

import json
import time

import numpy as np
import pyarrow as pa
import pytest

import delta_tpu.api as dta
from delta_tpu import obs
from delta_tpu.connect import DeltaConnectServer, connect
from delta_tpu.engine.host import HostEngine
from delta_tpu.obs.slo import Objective
from delta_tpu.resilience import ChaosSchedule, ChaosStore
from delta_tpu.serve import DeltaServeServer, ServeConfig
from delta_tpu.storage.logstore import InMemoryLogStore


@pytest.fixture
def tracing():
    obs.reset_trace_buffer()
    obs.set_trace_mode("on")
    yield
    obs.set_trace_mode("off")
    obs.reset_trace_buffer()


def _data(n=10):
    return pa.table({"x": pa.array(np.arange(n, dtype=np.int64))})


def _by_name(spans, name):
    return [s for s in spans if s.name == name]


# ------------------------------------------------------------ exposition


def test_prometheus_render_parse_round_trip():
    c = obs.counter("test.expose.hits")
    c.reset()
    c.inc(7)
    g = obs.gauge("test.expose.depth")
    g.set(3)
    text = obs.render_prometheus()
    series = obs.parse_prometheus(text)
    assert series["delta_tpu_test_expose_hits_total"] == 7.0
    assert series["delta_tpu_test_expose_depth"] == 3.0
    assert text.startswith("#") or text.startswith("delta_tpu_")


def test_prometheus_histogram_buckets_cumulative():
    h = obs.histogram("test.expose.lat")
    h.reset()
    for v in (0.5, 3.0, 7.0, 40.0, 1e12):  # last one overflows +Inf
        h.observe(v)
    text = obs.render_prometheus()
    series = obs.parse_prometheus(text)
    name = "delta_tpu_test_expose_lat"
    assert series[f'{name}_bucket{{le="1.0"}}'] == 1.0
    assert series[f'{name}_bucket{{le="5.0"}}'] == 2.0
    assert series[f'{name}_bucket{{le="10.0"}}'] == 3.0
    assert series[f'{name}_bucket{{le="50.0"}}'] == 4.0
    assert series[f'{name}_bucket{{le="+Inf"}}'] == 5.0
    assert series[f"{name}_count"] == 5.0
    assert series[f"{name}_sum"] == pytest.approx(0.5 + 3 + 7 + 40 + 1e12)
    # cumulative: each bucket >= the previous
    bounds = [f'{name}_bucket{{le="{repr(b)}"}}'
              for b in obs.EXPORT_BUCKETS]
    values = [series[k] for k in bounds]
    assert values == sorted(values)


def test_prometheus_catalog_zero_fill(tmp_path, monkeypatch):
    """Catalogued-but-untouched instruments render as zero with HELP
    text, so the scrape shape does not depend on import order."""
    cat = {"counters": {"test.never.touched": "Fixture help."},
           "histograms": {"test.never.lat": "Fixture histogram."},
           "gauges": {"test.never.depth": "Fixture gauge."}}
    path = tmp_path / "cat.json"
    path.write_text(json.dumps(cat))
    monkeypatch.setenv("DELTA_LINT_METRIC_CATALOG", str(path))
    text = obs.render_prometheus()
    series = obs.parse_prometheus(text)
    assert series["delta_tpu_test_never_touched_total"] == 0.0
    assert series["delta_tpu_test_never_depth"] == 0.0
    assert series['delta_tpu_test_never_lat_bucket{le="+Inf"}'] == 0.0
    assert "# HELP delta_tpu_test_never_touched_total Fixture help." in text


def test_repo_catalog_covered_by_exposition():
    """Every catalogued metric appears in a live scrape (the zero-fill
    union), including the serve/replay/resilience/parallel families the
    acceptance checklist names."""
    series = obs.parse_prometheus(obs.render_prometheus())
    catalog = obs.metric_catalog()
    for dotted in catalog["counters"]:
        assert obs.prom_name(dotted, "_total") in series, dotted
    for dotted in catalog["gauges"]:
        assert obs.prom_name(dotted) in series, dotted
    for dotted in catalog["histograms"]:
        assert obs.prom_name(dotted) + "_count" in series, dotted
    for expected in ("server.requests", "server.shed", "replay.h2d_bytes",
                     "storage.retry.attempts", "chaos.faults"):
        assert expected in catalog["counters"], expected


def test_gauge_callback_and_failure_renders_zero():
    g = obs.gauge("test.expose.cb")
    items = [1, 2, 3]
    g.set_fn(lambda: len(items))
    snap = obs.metrics_snapshot()
    assert snap["gauges"]["test.expose.cb"] == 3

    def boom():
        raise RuntimeError("torn down")

    g.set_fn(boom)
    assert g.read() is None  # swallowed, never raises
    series = obs.parse_prometheus(obs.render_prometheus())
    assert series["delta_tpu_test_expose_cb"] == 0.0
    g.set(0)  # unbind for later tests


# -------------------------------------------------------- flight recorder


def test_flight_recorder_assembles_and_dumps(tmp_path, tracing):
    rec = obs.FlightRecorder(max_traces=2)
    obs.add_exporter(rec)
    try:
        ids = []
        for i in range(3):
            with obs.span("req", i=i) as root:
                with obs.span("inner"):
                    pass
                ids.append(root.trace_id)
    finally:
        obs.remove_exporter(rec)
    # ring bound: the oldest trace rolled off
    assert len(rec) == 2
    assert rec.get(ids[0]) is None
    trace = rec.get(ids[2])
    assert [d["name"] for d in trace] == ["inner", "req"]
    assert all(d["trace_id"] == ids[2] for d in trace)
    # dump -> delta-trace-readable JSONL
    path = str(tmp_path / "flight.jsonl")
    n = rec.dump_jsonl(path, trace_id=ids[2])
    assert n == 2
    recs = obs.load_spans(path)
    assert {r["name"] for r in recs} == {"inner", "req"}
    # whole-ring dump covers both retained traces
    assert rec.dump_jsonl(path) == 4


def test_flight_recorder_root_names_complete_remote_traces(tracing):
    """A span named in root_names completes its trace even with a
    remote parent — the server-side root finishes before the client's
    (out-of-process) parent ever could."""
    rec = obs.FlightRecorder(root_names={"serve.request"})
    obs.add_exporter(rec)
    try:
        with obs.remote_parent("ab" * 16, "cd" * 8):
            with obs.span("serve.request") as root:
                with obs.span("serve.dispatch"):
                    pass
    finally:
        obs.remove_exporter(rec)
    trace = rec.get(root.trace_id)
    assert trace is not None
    assert {d["name"] for d in trace} == {"serve.request", "serve.dispatch"}
    (req,) = [d for d in trace if d["name"] == "serve.request"]
    assert req["trace_id"] == "ab" * 16
    assert req["parent_id"] == "cd" * 8


# ------------------------------------------------------------- SLO engine


def test_slo_burn_rate_needs_both_windows_and_min_events():
    now = [1000.0]
    eng = obs.SloEngine(
        [Objective(name="shed_rate", budget=0.05,
                   bad_outcomes=frozenset({"shed"}))],
        short_window_s=5.0, long_window_s=60.0, burn_threshold=1.0,
        min_events=20, clock=lambda: now[0])
    # cold window: 100% bad but below min_events -> no breach
    for _ in range(10):
        eng.record("shed", 1.0)
    assert eng.evaluate().ok
    # sustained burn across both windows
    for _ in range(30):
        eng.record("ok", 1.0)
        eng.record("shed", 1.0, trace_id="deadbeef")
        now[0] += 0.1
    verdict = eng.evaluate()
    assert not verdict.ok
    (breach,) = verdict.breaches
    assert breach.objective == "shed_rate"
    assert breach.burn_long > 1.0 and breach.burn_short > 1.0
    assert breach.worst_trace_id == "deadbeef"
    # burn stopped: the short window recovers first and the gate clears
    for _ in range(200):
        eng.record("ok", 1.0)
        now[0] += 0.05
    assert eng.evaluate().ok
    d = verdict.to_dict()
    assert d["ok"] is False and d["breaches"][0]["objective"] == "shed_rate"


def test_slo_p99_latency_objective_via_ratio():
    now = [0.0]
    eng = obs.SloEngine(
        obs.serve_objectives(p99_ms=50.0),
        short_window_s=5.0, long_window_s=30.0, min_events=20,
        clock=lambda: now[0])
    # 10% of events above threshold = 10x the 1% p99 budget
    for i in range(100):
        eng.record("ok", 500.0 if i % 10 == 0 else 5.0,
                   trace_id=f"{i:032x}")
        now[0] += 0.05
    verdict = eng.evaluate()
    assert not verdict.ok
    (breach,) = verdict.breaches
    assert breach.objective == "p99_latency"
    assert breach.burn_long == pytest.approx(10.0, rel=0.5)
    assert breach.worst_trace_id is not None


def test_serve_objectives_zero_disables():
    objs = obs.serve_objectives()
    assert objs == []
    objs = obs.serve_objectives(p99_ms=10.0, shed_rate=0.02)
    assert [o.name for o in objs] == ["p99_latency", "shed_rate"]
    events_pruned = obs.SloEngine(objs, clock=lambda: 0.0)
    events_pruned.record("ok", 1.0)
    assert events_pruned.event_count() == 1
    events_pruned.reset()
    assert events_pruned.event_count() == 0


# ----------------------------------------- cross-process trace adoption


def test_remote_parent_rejects_garbage_and_off_mode():
    obs.set_trace_mode("off")
    ctx = obs.remote_parent("ab" * 16, "cd" * 8)
    with ctx as s:
        assert not s.recording
    obs.set_trace_mode("on")
    try:
        for bad in (None, 42, "", "x" * 65, "zz<script>", b"abc"):
            with obs.remote_parent(bad, "cd" * 8) as s:
                assert not s.recording
            with obs.remote_parent("ab" * 16, bad) as s:
                assert not s.recording
        with obs.remote_parent("ab" * 16, "cd" * 8):
            with obs.span("child") as child:
                assert child.trace_id == "ab" * 16
                assert child.parent_id == "cd" * 8
        assert obs.trace_context() is None  # adoption fully unwound
    finally:
        obs.set_trace_mode("off")
        obs.reset_trace_buffer()


# ---------------------------------------------------- head-based sampling


@pytest.fixture
def sampled_off(tracing):
    obs.set_trace_sample(0.0)
    yield
    obs.set_trace_sample(1.0)


def test_unsampled_trace_is_dropped_whole(sampled_off):
    """Sampling decides at the trace ROOT: an unsampled root suppresses
    every descendant (same thread, wrapped threads) so no parent-less
    fragments ever reach the buffer."""
    import threading

    seen = []

    def worker():
        with obs.span("thread.child"):
            seen.append(obs.current_span())

    with obs.span("root") as s:
        assert not s.recording
        assert obs.current_span() is None
        assert obs.trace_context() is None  # no envelope stamping
        obs.set_attr("k", 1)  # safe no-ops under suppression
        obs.add_event("e")
        with obs.span("child") as c:
            assert not c.recording
        t = threading.Thread(target=obs.wrap(worker))
        t.start()
        t.join()
    assert seen == [None]
    assert obs.get_finished_spans() == []
    # suppression fully unwinds: the next root records again
    obs.set_trace_sample(1.0)
    with obs.span("after") as s:
        assert s.recording
    assert [s.name for s in obs.get_finished_spans()] == ["after"]


def test_set_trace_sample_clamps_and_rereads_env(monkeypatch, tracing):
    obs.set_trace_sample(7.5)
    assert obs.trace_sample() == 1.0
    obs.set_trace_sample(-2)
    assert obs.trace_sample() == 0.0
    monkeypatch.setenv("DELTA_TPU_TRACE_SAMPLE", "0.25")
    obs.set_trace_sample(None)
    assert obs.trace_sample() == 0.25
    monkeypatch.setenv("DELTA_TPU_TRACE_SAMPLE", "nonsense")
    obs.set_trace_sample(None)
    assert obs.trace_sample() == 1.0
    obs.set_trace_sample(1.0)


def test_unsampled_client_request_emits_no_spans(sampled_off):
    """End to end at sample rate 0: the client root is suppressed, the
    envelope carries no trace ids, and the in-process server inherits
    the zero rate — the whole request leaves zero spans behind."""
    eng = _mem_engine(seed=7)
    srv = _serve_server(eng, workers=2, max_queue=8)
    try:
        host, port = srv.address
        path = "memory://telemetry-unsampled"
        dta.write_table(path, _data(), engine=eng)
        obs.reset_trace_buffer()
        with connect(host, port, reconnect=False) as c:
            assert c.read_table(path).num_rows == 10
        time.sleep(0.1)  # let any stray server-side span land
        assert obs.get_finished_spans() == []
    finally:
        srv.shutdown(1.0)


def _serve_server(engine, **cfg):
    cfg.setdefault("drain_grace_s", 5.0)
    srv = DeltaServeServer("127.0.0.1", 0, engine=engine,
                           config=ServeConfig.from_env(**cfg))
    return srv.start_background()


def _mem_engine(seed=1):
    store = ChaosStore(InMemoryLogStore(), ChaosSchedule(seed),
                       sleep=lambda s: None)
    store.enabled = False
    return HostEngine(store_resolver=lambda p: store)


def _assert_single_connected_trace(spans, client_root):
    """Every span shares client_root's trace id and walks up to it."""
    assert all(s.trace_id == client_root.trace_id for s in spans)
    by_id = {s.span_id: s for s in spans}
    by_id[client_root.span_id] = client_root
    for s in spans:
        node, hops = s, 0
        while node.parent_id is not None and hops < 100:
            assert node.parent_id in by_id, \
                f"{s.name}: broken parent link at {node.name}"
            node = by_id[node.parent_id]
            hops += 1
        assert node.span_id == client_root.span_id


@pytest.mark.parametrize("server_kind", ["connect", "serve"])
def test_one_trace_id_across_client_and_server(server_kind, tmp_path,
                                               tracing):
    """Acceptance: a client request produces ONE trace whose server-side
    spans (request root, dispatch, snapshot work) parent under the
    client's connect.attempt span — on both server variants."""
    if server_kind == "connect":
        eng = HostEngine()
        srv = DeltaConnectServer("127.0.0.1", 0, engine=eng,
                                 allowed_root=str(tmp_path)).start_background()
        stop = srv.stop
        request_root = "connect.request"
        path = str(tmp_path / "t")
    else:
        eng = _mem_engine()
        srv = _serve_server(eng, workers=2, max_queue=8)
        stop = lambda: srv.shutdown(1.0)  # noqa: E731
        request_root = "serve.request"
        path = "memory://telemetry-e2e"
    try:
        host, port = srv.address
        dta.write_table(path, _data(), engine=eng)
        obs.reset_trace_buffer()
        with connect(host, port, reconnect=False) as c:
            assert c.read_table(path).num_rows == 10
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            spans = obs.get_finished_spans()
            if _by_name(spans, "connect.call"):
                break
            time.sleep(0.01)
        (call,) = _by_name(spans, "connect.call")
        (attempt,) = _by_name(spans, "connect.attempt")
        (req,) = _by_name(spans, request_root)
        assert attempt.parent_id == call.span_id
        # the server-side request root adopted the attempt as parent
        assert req.trace_id == call.trace_id
        assert req.parent_id == attempt.span_id
        # snapshot work joined the same trace
        assert any(s.trace_id == call.trace_id
                   for s in _by_name(spans, "snapshot.load"))
        others = [s for s in spans if s is not call]
        _assert_single_connected_trace(others, call)
    finally:
        stop()


def test_serve_flight_recorder_retrievable_by_trace_id(tracing):
    """The serve server's armed flight recorder retains the complete
    request trace, retrievable by the client's trace id."""
    eng = _mem_engine(seed=2)
    srv = _serve_server(eng, workers=2, max_queue=8)  # armed: tracing on
    try:
        host, port = srv.address
        path = "memory://telemetry-flight"
        dta.write_table(path, _data(), engine=eng)
        obs.reset_trace_buffer()
        with connect(host, port, reconnect=False) as c:
            assert c.read_table(path).num_rows == 10
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            calls = _by_name(obs.get_finished_spans(), "connect.call")
            if calls and srv.flight.get(calls[0].trace_id):
                break
            time.sleep(0.01)
        (call,) = calls
        trace = srv.flight.get(call.trace_id)
        assert trace is not None
        names = {d["name"] for d in trace}
        assert "serve.request" in names and "serve.dispatch" in names
    finally:
        srv.shutdown(1.0)


def test_hedged_read_losing_attempt_is_distinct_branch(tracing):
    """Both hedge attempts share the call's trace id but are SIBLING
    branches: distinct span ids, each the root of its own server-side
    subtree."""
    store = ChaosStore(InMemoryLogStore(),
                       ChaosSchedule(21, latency_rate=1.0,
                                     latency_s=(0.03, 0.04)),
                       sleep=time.sleep)
    store.enabled = False
    eng = HostEngine(store_resolver=lambda p: store)
    srv = _serve_server(eng, workers=4, max_queue=16)
    try:
        host, port = srv.address
        path = "memory://telemetry-hedge"
        dta.write_table(path, _data(12), engine=eng)
        store.enabled = True  # slow enough that the hedge always fires
        obs.reset_trace_buffer()
        with connect(host, port, hedge_ms=5.0) as c:
            assert c.read_table(path).num_rows == 12
        deadline = time.monotonic() + 10
        attempts = []
        while time.monotonic() < deadline:
            spans = obs.get_finished_spans()
            attempts = [s for s in _by_name(spans, "connect.attempt")
                        if s.attrs.get("op") == "read"]
            if len(attempts) >= 2:
                break
            time.sleep(0.02)
        assert len(attempts) >= 2, "hedge attempt never fired"
        (call,) = [s for s in _by_name(spans, "connect.call")
                   if s.attrs.get("op") == "read"]
        assert len({a.span_id for a in attempts}) == len(attempts)
        for a in attempts:
            assert a.trace_id == call.trace_id
            assert a.parent_id == call.span_id
        # each server-side request root hangs under a DIFFERENT attempt
        reqs = [s for s in _by_name(spans, "serve.request")
                if s.attrs.get("op") == "read"
                and s.trace_id == call.trace_id]
        assert len(reqs) >= 2
        parents = {r.parent_id for r in reqs}
        assert parents <= {a.span_id for a in attempts}
        assert len(parents) >= 2
    finally:
        srv.shutdown(1.0)


# ------------------------------------------------------ metrics scraping


def test_serve_inline_metrics_scrape():
    eng = _mem_engine(seed=3)
    srv = _serve_server(eng, workers=1, max_queue=4)
    try:
        host, port = srv.address
        path = "memory://telemetry-scrape"
        dta.write_table(path, _data(), engine=eng)
        before = obs.counter("server.requests").value
        with connect(host, port, reconnect=False) as c:
            assert c.read_table(path).num_rows == 10
            text = c.metrics_text()
        series = obs.parse_prometheus(text)
        assert series["delta_tpu_server_requests_total"] >= before + 1
        assert "delta_tpu_server_queue_depth" in series
        assert "delta_tpu_replay_resident_hbm_bytes" in series
    finally:
        srv.shutdown(1.0)


def test_metrics_op_bypasses_full_admission_queue():
    """A scrape answers even when the admission queue sheds everything
    (max_queue=0): observability of an overloaded server is the point."""
    eng = _mem_engine(seed=4)
    srv = _serve_server(eng, workers=1, max_queue=0)
    try:
        host, port = srv.address
        with connect(host, port, reconnect=False) as c:
            text = c.metrics_text()
        assert "delta_tpu_server_requests_total" in text
    finally:
        srv.shutdown(1.0)


def test_metrics_cli_remote_and_local(capsys):
    from delta_tpu.tools.metrics_cli import main as metrics_main

    eng = _mem_engine(seed=5)
    srv = _serve_server(eng, workers=1, max_queue=4)
    try:
        host, port = srv.address
        assert metrics_main(["--connect", f"{host}:{port}"]) == 0
        out = capsys.readouterr().out
        assert "delta_tpu_server_requests_total" in out
        assert metrics_main(["--connect", f"{host}:{port}", "--json",
                             "--grep", "server_conn"]) == 0
        doc = json.loads(capsys.readouterr().out)
        assert all("server_conn" in k for k in doc)
        assert doc  # the accepted-connections series survived the grep
    finally:
        srv.shutdown(1.0)
    assert metrics_main(["--local", "--grep", "parse_cache"]) == 0
    assert "parse_cache" in capsys.readouterr().out
    # unreachable target: diagnostic on stderr, exit 2
    assert metrics_main(["--connect", "127.0.0.1:1", "--timeout",
                         "0.2"]) == 2
    assert "delta-metrics:" in capsys.readouterr().err


# ----------------------------------------------- SLO gates on the server


def _slo_serve(engine, **cfg):
    cfg.setdefault("drain_grace_s", 5.0)
    cfg.setdefault("slo_p99_ms", 30_000.0)
    cfg.setdefault("slo_shed_rate", 0.05)
    srv = DeltaServeServer("127.0.0.1", 0, engine=engine,
                           config=ServeConfig.from_env(**cfg))
    return srv.start_background()


def test_serve_slo_verdict_clean_and_breach(tmp_path):
    eng = _mem_engine(seed=6)
    srv = _slo_serve(eng, workers=1, max_queue=0,  # everything sheds
                     slo_dump_dir=str(tmp_path))
    try:
        # widen the gate for test speed: the engine defaults to 60s
        # windows / 20 events, which a unit test should not wait out
        srv.slo.min_events = 5
        host, port = srv.address
        with connect(host, port, reconnect=False) as c:
            for _ in range(8):
                try:
                    c.table_version("memory://nope")
                except Exception:
                    pass
        verdict = srv.slo_verdict()
        assert verdict is not None and not verdict.ok
        assert any(b.objective == "shed_rate" for b in verdict.breaches)
        with connect(host, port, reconnect=False) as c:
            h = c.health()
        assert h["slo"]["ok"] is False
    finally:
        srv.shutdown(1.0)


def test_serve_slo_disabled_by_default():
    eng = _mem_engine(seed=7)
    srv = _serve_server(eng, workers=1, max_queue=4)
    try:
        assert srv.slo is None and srv.slo_verdict() is None
        with connect(*srv.address, reconnect=False) as c:
            assert "slo" not in c.health()
    finally:
        srv.shutdown(1.0)


def test_slo_breach_dumps_flight_trace(tmp_path, tracing):
    """An SLO breach writes the offending trace from the flight ring
    as a delta-trace-readable JSONL dump."""
    eng = _mem_engine(seed=8)
    srv = _slo_serve(eng, workers=2, max_queue=8,
                     slo_p99_ms=0.0001,  # everything breaches p99
                     slo_dump_dir=str(tmp_path))
    try:
        srv.slo.min_events = 5
        host, port = srv.address
        path = "memory://telemetry-slo-dump"
        dta.write_table(path, _data(), engine=eng)
        with connect(host, port, reconnect=False) as c:
            for _ in range(10):
                c.read_table(path)
                time.sleep(0.03)  # straddle the evaluation cadence
        dump = tmp_path / "flight_p99_latency.jsonl"
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and not dump.exists():
            time.sleep(0.05)
        assert dump.exists(), "breach produced no flight dump"
        recs = obs.load_spans(str(dump))
        assert recs and any(r["name"] == "serve.request" for r in recs)
        assert obs.counter("server.slo_breaches").value > 0
    finally:
        srv.shutdown(1.0)


# ------------------------------------------------- Chrome process groups


def test_chrome_export_groups_by_process(tmp_path, tracing):
    """Spans carrying different process labels land in different Chrome
    pid groups, each with a process_name metadata event."""
    obs.set_process_label("delta-serve")
    try:
        with obs.span("serve.request"):
            pass
    finally:
        obs.set_process_label(None)
    with obs.span("connect.call"):
        pass
    spans = obs.get_finished_spans()
    serve_d = [s.to_dict() for s in _by_name(spans, "serve.request")][0]
    client_d = [s.to_dict() for s in _by_name(spans, "connect.call")][0]
    assert serve_d["process"] == "delta-serve"
    assert client_d["process"] is None
    # simulate the cross-process case: the server ran elsewhere
    serve_d["pid"] = serve_d["pid"] + 1
    doc = json.loads(json.dumps(obs.chrome_trace([serve_d, client_d])))
    xs = {e["name"]: e for e in doc["traceEvents"] if e["ph"] == "X"}
    assert xs["serve.request"]["pid"] != xs["connect.call"]["pid"]
    procs = {e["pid"]: e["args"]["name"] for e in doc["traceEvents"]
             if e["ph"] == "M" and e["name"] == "process_name"}
    assert procs[xs["serve.request"]["pid"]] == "delta-serve"
    assert xs["connect.call"]["pid"] in procs
    # thread_name metadata exists per (pid, tid) group
    assert any(e["ph"] == "M" and e["name"] == "thread_name"
               for e in doc["traceEvents"])
    # round-trip: pid/process survive the Chrome shape
    path = str(tmp_path / "multi.json")
    obs.write_chrome_trace(path, [serve_d, client_d])
    back = obs.load_spans(path)
    by = {r["name"]: r for r in back}
    assert by["serve.request"]["pid"] == serve_d["pid"]


# --------------------------------------------------------- disabled path


def test_disabled_path_overhead_is_noop():
    """With tracing off the serve path must not allocate spans: the
    span() fast path returns the shared no-op singleton."""
    obs.set_trace_mode("off")
    assert obs.trace_context() is None
    ctx1 = obs.span("serve.request")  # delta-lint: disable=obs-span-leak — singleton identity check
    ctx2 = obs.remote_parent("ab" * 16, "cd" * 8)
    assert ctx1 is ctx2  # same process-wide singleton, zero allocation
    eng = _mem_engine(seed=9)
    srv = _serve_server(eng, workers=1, max_queue=4)
    try:
        assert not srv._flight_installed  # recorder not armed when off
        path = "memory://telemetry-off"
        dta.write_table(path, _data(), engine=eng)
        with connect(*srv.address, reconnect=False) as c:
            assert c.read_table(path).num_rows == 10
        assert len(srv.flight) == 0
        assert obs.get_finished_spans() == []
    finally:
        srv.shutdown(1.0)

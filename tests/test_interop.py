"""Avro codec, Iceberg UniForm export, Hudi export."""

import json
import os

import numpy as np
import pyarrow as pa
import pytest

import delta_tpu.api as dta
from delta_tpu.interop import avro as avro_io
from delta_tpu.table import Table


def test_avro_roundtrip_primitives():
    schema = {
        "type": "record",
        "name": "t",
        "fields": [
            {"name": "b", "type": "boolean"},
            {"name": "i", "type": "int"},
            {"name": "l", "type": "long"},
            {"name": "f", "type": "float"},
            {"name": "d", "type": "double"},
            {"name": "s", "type": "string"},
            {"name": "by", "type": "bytes"},
            {"name": "u", "type": ["null", "long"]},
            {"name": "arr", "type": {"type": "array", "items": "int"}},
            {"name": "m", "type": {"type": "map", "values": "string"}},
        ],
    }
    records = [
        {"b": True, "i": -5, "l": 2**40, "f": 1.5, "d": -2.25, "s": "héllo",
         "by": b"\x00\x01", "u": None, "arr": [1, 2, 3], "m": {"k": "v"}},
        {"b": False, "i": 0, "l": -1, "f": 0.0, "d": 1e300, "s": "",
         "by": b"", "u": 77, "arr": [], "m": {}},
    ]
    data = avro_io.write_ocf(schema, records)
    schema2, back, meta = avro_io.read_ocf(data)
    assert schema2 == schema
    assert back[0]["s"] == "héllo"
    assert back[0]["arr"] == [1, 2, 3]
    assert back[1]["u"] == 77
    assert back[1]["d"] == 1e300
    assert back[0]["l"] == 2**40


def test_avro_zigzag_longs():
    import io

    for n in [0, -1, 1, 63, -64, 2**62, -(2**62)]:
        buf = io.BytesIO()
        avro_io.write_long(buf, n)
        buf.seek(0)
        assert avro_io.read_long(buf) == n


def _mk(path, partition=False, props=None):
    data = pa.table(
        {
            "id": pa.array(np.arange(100, dtype=np.int64)),
            "p": pa.array(["a"] * 50 + ["b"] * 50),
        }
    )
    dta.write_table(
        path, data,
        partition_by=["p"] if partition else None,
        properties=props,
    )
    return Table.for_path(path)


def test_iceberg_conversion_structure(tmp_table_path):
    table = _mk(tmp_table_path, partition=True,
                props={"delta.universalFormat.enabledFormats": "iceberg"})
    meta_dir = os.path.join(tmp_table_path, "metadata")
    assert os.path.isdir(meta_dir)
    with open(os.path.join(meta_dir, "version-hint.text")) as f:
        v = int(f.read())
    with open(os.path.join(meta_dir, f"v{v}.metadata.json")) as f:
        md = json.load(f)
    assert md["format-version"] == 2
    assert md["current-snapshot-id"] == 1
    snap_entry = md["snapshots"][0]
    # manifest list resolves and matches
    _, manifests, _ = avro_io.read_ocf(open(snap_entry["manifest-list"], "rb").read())
    assert manifests[0]["added_files_count"] == 2  # one file per partition
    # manifest entries point at real parquet files with typed partitions
    _, entries, mmeta = avro_io.read_ocf(open(manifests[0]["manifest_path"], "rb").read())
    assert len(entries) == 2
    for e in entries:
        assert os.path.exists(e["data_file"]["file_path"])
        assert e["data_file"]["file_format"] == "PARQUET"
        assert e["data_file"]["partition"]["p"] in ("a", "b")
        assert e["data_file"]["record_count"] == 50
    ice_schema = json.loads(mmeta["schema"])
    assert [f["name"] for f in ice_schema["fields"]] == ["id", "p"]
    assert all("id" in f for f in ice_schema["fields"])


def test_iceberg_conversion_advances(tmp_table_path):
    table = _mk(tmp_table_path,
                props={"delta.universalFormat.enabledFormats": "iceberg"})
    dta.write_table(
        tmp_table_path,
        pa.table({"id": pa.array([1], pa.int64()), "p": pa.array(["c"])}),
    )
    meta_dir = os.path.join(tmp_table_path, "metadata")
    with open(os.path.join(meta_dir, "version-hint.text")) as f:
        assert int(f.read()) == 2
    with open(os.path.join(meta_dir, "v2.metadata.json")) as f:
        md = json.load(f)
    assert md["properties"]["delta.version"] == "1"


def test_hudi_conversion(tmp_table_path):
    _mk(tmp_table_path, partition=True,
        props={"delta.universalFormat.enabledFormats": "hudi"})
    hoodie = os.path.join(tmp_table_path, ".hoodie")
    assert os.path.exists(os.path.join(hoodie, "hoodie.properties"))
    commits = [f for f in os.listdir(hoodie) if f.endswith(".commit")]
    assert len(commits) == 1
    with open(os.path.join(hoodie, commits[0])) as f:
        doc = json.load(f)
    parts = doc["partitionToWriteStats"]
    assert set(parts) == {"p=a", "p=b"}


# ------------------------------------------------------- iceberg compat

def test_iceberg_compat_v2_validation(tmp_table_path):
    import numpy as np
    import pyarrow as pa
    import pytest

    import delta_tpu.api as dta
    from delta_tpu.errors import DeltaError

    data = pa.table({"x": pa.array(np.arange(3, dtype=np.int64))})
    # compat requires column mapping
    with pytest.raises(DeltaError, match="column mapping"):
        dta.write_table(tmp_table_path + "_a", data,
                        properties={"delta.enableIcebergCompatV2": "true"})
    # with mapping on, the commit passes and the feature is activated
    dta.write_table(tmp_table_path, data, properties={
        "delta.enableIcebergCompatV2": "true",
        "delta.columnMapping.mode": "name"})
    from delta_tpu.table import Table

    snap = Table.for_path(tmp_table_path).latest_snapshot()
    assert "icebergCompatV2" in (snap.protocol.writerFeatures or [])
    # DVs cannot be enabled together with compat
    with pytest.raises(DeltaError, match="deletion"):
        dta.write_table(tmp_table_path + "_b", data, properties={
            "delta.enableIcebergCompatV2": "true",
            "delta.columnMapping.mode": "name",
            "delta.enableDeletionVectors": "true"})


def test_iceberg_compat_versions_mutually_exclusive(tmp_table_path):
    import numpy as np
    import pyarrow as pa
    import pytest

    import delta_tpu.api as dta
    from delta_tpu.errors import DeltaError

    with pytest.raises(DeltaError, match="mutually exclusive"):
        dta.write_table(
            tmp_table_path, pa.table({"x": pa.array([1], pa.int64())}),
            properties={"delta.enableIcebergCompatV1": "true",
                        "delta.enableIcebergCompatV2": "true",
                        "delta.columnMapping.mode": "name"})


def test_iceberg_incremental_append_reuses_manifests(tmp_table_path):
    """An append converts into a NEW manifest while the previous
    manifest is reused untouched (IcebergConversionTransaction's append
    path), with snapshot lineage + logs."""
    _mk(tmp_table_path,
        props={"delta.universalFormat.enabledFormats": "iceberg"})
    meta_dir = os.path.join(tmp_table_path, "metadata")
    with open(os.path.join(meta_dir, "v1.metadata.json")) as f:
        md1 = json.load(f)
    snap1 = md1["snapshots"][-1]
    _, manifests1, _ = avro_io.read_ocf(
        open(snap1["manifest-list"], "rb").read())

    dta.write_table(tmp_table_path, pa.table(
        {"id": pa.array([100], pa.int64()), "p": pa.array(["z"])}),
        mode="append")
    with open(os.path.join(meta_dir, "v2.metadata.json")) as f:
        md2 = json.load(f)
    assert len(md2["snapshots"]) == 2
    snap2 = md2["snapshots"][-1]
    assert snap2["parent-snapshot-id"] == snap1["snapshot-id"]
    assert snap2["summary"]["operation"] == "append"
    assert [e["snapshot-id"] for e in md2["snapshot-log"]] == [
        s["snapshot-id"] for s in md2["snapshots"]]
    assert md2["metadata-log"][-1]["metadata-file"].endswith(
        "v1.metadata.json")

    _, manifests2, _ = avro_io.read_ocf(
        open(snap2["manifest-list"], "rb").read())
    # previous manifest path appears unchanged + one new ADDED manifest
    prev_paths = {m["manifest_path"] for m in manifests1}
    assert prev_paths <= {m["manifest_path"] for m in manifests2}
    new = [m for m in manifests2 if m["manifest_path"] not in prev_paths]
    assert len(new) == 1 and new[0]["added_files_count"] == 1


def test_iceberg_incremental_delete_rewrites_touched_manifest(tmp_table_path):
    from delta_tpu.commands.dml import delete
    from delta_tpu.expressions import col, lit

    _mk(tmp_table_path,
        props={"delta.universalFormat.enabledFormats": "iceberg"})
    delete(Table.for_path(tmp_table_path), predicate=col("p") == lit("a"))
    meta_dir = os.path.join(tmp_table_path, "metadata")
    with open(os.path.join(meta_dir, "v2.metadata.json")) as f:
        md = json.load(f)
    snap = md["snapshots"][-1]
    assert snap["summary"]["operation"] in ("delete", "overwrite")
    _, manifests, _ = avro_io.read_ocf(
        open(snap["manifest-list"], "rb").read())
    # the rewritten manifest marks the removed file DELETED
    statuses = []
    for m in manifests:
        _, entries, _ = avro_io.read_ocf(
            open(m["manifest_path"], "rb").read())
        statuses += [e["status"] for e in entries]
    assert 2 in statuses  # DELETED entry present


def test_iceberg_incremental_remove_then_readd_no_duplicate(tmp_table_path):
    """A remove-then-re-add of the same path inside one conversion window
    (e.g. DELETE then RESTORE) must not leave the file live in both a
    reused manifest and the new ADDED manifest (advisor round-2 medium)."""
    from delta_tpu.commands.dml import delete
    from delta_tpu.commands.restore import restore
    from delta_tpu.expressions import col, lit
    import delta_tpu.interop.iceberg as ice

    table = _mk(tmp_table_path, partition=True)  # no auto-convert
    ice.convert_snapshot(table.latest_snapshot())  # window anchor at v0

    delete(Table.for_path(tmp_table_path), predicate=col("p") == lit("a"))
    restore(Table.for_path(tmp_table_path), version=0)
    snap = Table.for_path(tmp_table_path).latest_snapshot()
    md_path = ice.convert_snapshot(snap)  # window = v1..v2 (remove + re-add)

    with open(md_path) as f:
        md = json.load(f)
    cur = next(s for s in md["snapshots"]
               if s["snapshot-id"] == md["current-snapshot-id"])
    _, manifests, _ = avro_io.read_ocf(open(cur["manifest-list"], "rb").read())
    live = []
    for m in manifests:
        _, entries, _ = avro_io.read_ocf(
            open(m["manifest_path"], "rb").read())
        live += [e["data_file"]["file_path"] for e in entries
                 if e["status"] != 2]
    assert len(live) == len(set(live)), f"duplicate live entries: {live}"
    delta_live = {
        p if ("://" in p or p.startswith("/"))
        else f"{tmp_table_path}/{p}"
        for p in snap.state.add_files_table.column("path").to_pylist()}
    assert set(live) == delta_live


def test_iceberg_schema_evolution_bumps_schema_id(tmp_table_path):
    _mk(tmp_table_path,
        props={"delta.universalFormat.enabledFormats": "iceberg"})
    dta.write_table(tmp_table_path, pa.table({
        "id": pa.array([5], pa.int64()),
        "p": pa.array(["a"]),
        "extra": pa.array([1.5]),
    }), mode="append", merge_schema=True)
    meta_dir = os.path.join(tmp_table_path, "metadata")
    with open(os.path.join(meta_dir, "v2.metadata.json")) as f:
        md = json.load(f)
    assert len(md["schemas"]) == 2
    assert md["current-schema-id"] == 1
    assert md["snapshots"][-1]["schema-id"] == 1  # snapshot binds new schema
    cur = next(s for s in md["schemas"] if s["schema-id"] == 1)
    assert [f["name"] for f in cur["fields"]] == ["id", "p", "extra"]


def test_iceberg_snapshot_expiry(tmp_table_path):
    import delta_tpu.interop.iceberg as ice

    _mk(tmp_table_path,
        props={"delta.universalFormat.enabledFormats": "iceberg"})
    old_retention = ice.SNAPSHOT_RETENTION
    ice.SNAPSHOT_RETENTION = 3
    try:
        for i in range(5):
            dta.write_table(tmp_table_path, pa.table(
                {"id": pa.array([i], pa.int64()),
                 "p": pa.array(["x"])}), mode="append")
    finally:
        ice.SNAPSHOT_RETENTION = old_retention
    meta_dir = os.path.join(tmp_table_path, "metadata")
    with open(os.path.join(meta_dir, "version-hint.text")) as f:
        v = int(f.read())
    with open(os.path.join(meta_dir, f"v{v}.metadata.json")) as f:
        md = json.load(f)
    assert len(md["snapshots"]) == 3
    keep = {s["snapshot-id"] for s in md["snapshots"]}
    assert {e["snapshot-id"] for e in md["snapshot-log"]} == keep
    # retained manifest lists resolve; every manifest they reference exists
    for s in md["snapshots"]:
        _, ms, _ = avro_io.read_ocf(open(s["manifest-list"], "rb").read())
        for m in ms:
            assert os.path.exists(m["manifest_path"])


def test_hudi_timeline_states_and_archival(tmp_table_path):
    import delta_tpu.interop.hudi as hudi

    _mk(tmp_table_path, partition=True,
        props={"delta.universalFormat.enabledFormats": "hudi"})
    hoodie = os.path.join(tmp_table_path, ".hoodie")
    commits = sorted(f for f in os.listdir(hoodie) if f.endswith(".commit"))
    assert len(commits) == 1
    instant = commits[0][:-len(".commit")]
    # full three-state lifecycle on disk
    assert os.path.exists(os.path.join(hoodie, f"{instant}.commit.requested"))
    assert os.path.exists(os.path.join(hoodie, f"{instant}.commit.inflight"))

    # incremental append: write stats cover ONLY the new file, linked to
    # the previous instant
    dta.write_table(tmp_table_path, pa.table(
        {"id": pa.array([7], pa.int64()), "p": pa.array(["a"])}),
        mode="append")
    commits = sorted(f for f in os.listdir(hoodie) if f.endswith(".commit"))
    assert len(commits) == 2
    with open(os.path.join(hoodie, commits[-1])) as f:
        doc = json.load(f)
    stats = [s for part in doc["partitionToWriteStats"].values() for s in part]
    assert len(stats) == 1
    assert stats[0]["prevCommit"] == commits[0][:-len(".commit")]
    assert doc["extraMetadata"]["delta.version"] == "1"

    # archival: drive past the cap and check instants moved to archived/
    old_cap = hudi.ACTIVE_TIMELINE_CAP
    hudi.ACTIVE_TIMELINE_CAP = 2
    try:
        for i in range(3):
            dta.write_table(tmp_table_path, pa.table(
                {"id": pa.array([i], pa.int64()), "p": pa.array(["b"])}),
                mode="append")
    finally:
        hudi.ACTIVE_TIMELINE_CAP = old_cap
    active = sorted(f for f in os.listdir(hoodie) if f.endswith(".commit"))
    assert len(active) == 2
    archived = os.listdir(os.path.join(hoodie, "archived"))
    assert any(a.endswith(".commit") for a in archived)


def test_hudi_delete_completes_as_replacecommit(tmp_table_path):
    """Removals must complete as a `replacecommit` instant — the only
    action whose replaced file groups Hudi readers honor."""
    from delta_tpu.commands.dml import delete
    from delta_tpu.expressions import col, lit

    _mk(tmp_table_path, partition=True,
        props={"delta.universalFormat.enabledFormats": "hudi"})
    delete(Table.for_path(tmp_table_path), predicate=col("p") == lit("a"))
    hoodie = os.path.join(tmp_table_path, ".hoodie")
    rc = sorted(f for f in os.listdir(hoodie)
                if f.endswith(".replacecommit"))
    assert len(rc) == 1
    instant = rc[0][:-len(".replacecommit")]
    assert os.path.exists(
        os.path.join(hoodie, f"{instant}.replacecommit.requested"))
    with open(os.path.join(hoodie,
                           f"{instant}.replacecommit.inflight")) as f:
        assert json.load(f)["operationType"] == "UPSERT"
    with open(os.path.join(hoodie, rc[0])) as f:
        doc = json.load(f)
    replaced = [fid for fids in doc["partitionToReplaceFileIds"].values()
                for fid in fids]
    assert replaced, "replaced file groups must be declared"
    assert "p=a" in doc["partitionToReplaceFileIds"]

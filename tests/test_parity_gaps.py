"""Column DEFAULTs, liquid clustering, row-tracking backfill, deep
clone, and the streaming schema-tracking log."""

import numpy as np
import pyarrow as pa
import pytest

import delta_tpu.api as dta
from delta_tpu.colgen import default_field
from delta_tpu.errors import DeltaError
from delta_tpu.models.schema import LONG, STRING, StructField, StructType
from delta_tpu.table import Table


def _write(path, start, n, extra_cols=None):
    cols = {"id": pa.array(np.arange(start, start + n, dtype=np.int64)),
            "v": pa.array(np.full(n, float(start)))}
    cols.update(extra_cols or {})
    dta.write_table(path, pa.table(cols), mode="append")


# ---------------------------------------------------------------- defaults

def test_column_defaults(tmp_table_path):
    schema = StructType([
        StructField("id", LONG, nullable=False),
        default_field("status", STRING, "'active'"),
        default_field("score", LONG, "100"),
    ])
    t = Table.for_path(tmp_table_path)
    t.create_transaction_builder().with_schema(schema).build().commit()
    dta.write_table(tmp_table_path,
                    pa.table({"id": pa.array([1, 2], pa.int64())}),
                    mode="append")
    rows = dta.read_table(tmp_table_path)
    assert rows.column("status").to_pylist() == ["active", "active"]
    assert rows.column("score").to_pylist() == [100, 100]
    snap = Table.for_path(tmp_table_path).latest_snapshot()
    assert "allowColumnDefaults" in (snap.protocol.writerFeatures or [])
    # explicit values win over the default
    dta.write_table(tmp_table_path,
                    pa.table({"id": pa.array([3], pa.int64()),
                              "status": pa.array(["x"]),
                              "score": pa.array([7], pa.int64())}),
                    mode="append")
    rows = dta.read_table(tmp_table_path)
    assert sorted(rows.column("score").to_pylist()) == [7, 100, 100]


# ---------------------------------------------------------------- clustering

def test_liquid_clustering_optimize(tmp_table_path):
    from delta_tpu.clustering import (
        CLUSTERING_DOMAIN,
        ZCUBE_ID_TAG,
        clustering_columns,
        set_clustering_columns,
    )

    for i in range(3):
        _write(tmp_table_path, i * 10, 10)
    table = Table.for_path(tmp_table_path)
    set_clustering_columns(table, ["id"])
    snap = table.latest_snapshot()
    assert clustering_columns(snap) == ["id"]
    assert "clustering" in (snap.protocol.writerFeatures or [])
    assert CLUSTERING_DOMAIN in snap.state.visible_domain_metadata()

    # plain OPTIMIZE clusters by the domain columns and tags outputs
    m = table.optimize().execute_compaction()
    assert m.num_files_removed == 3 and m.num_files_added >= 1
    snap = table.latest_snapshot()
    adds = snap.state.add_files()
    assert all((a.tags or {}).get(ZCUBE_ID_TAG) for a in adds)
    assert all(a.clusteringProvider == "liquid" for a in adds)
    # data intact and clustered (sorted by id within the file)
    rows = dta.read_table(tmp_table_path)
    assert sorted(rows.column("id").to_pylist()) == list(range(30))

    # explicit ZORDER BY on a clustered table is rejected
    with pytest.raises(DeltaError):
        table.optimize().execute_zorder_by("v")

    # CLUSTER BY NONE removes the domain
    set_clustering_columns(table, [])
    assert clustering_columns(Table.for_path(tmp_table_path).latest_snapshot()) is None


def test_stable_zcube_skip():
    from delta_tpu.clustering import (
        DEFAULT_MIN_CUBE_SIZE,
        file_in_stable_zcube,
        new_zcube_tags,
    )
    from delta_tpu.models.actions import AddFile

    tags = new_zcube_tags(["id"], "zorder")
    f = AddFile(path="p", partitionValues={}, size=10,
                modificationTime=0, dataChange=False, tags=tags)
    cube = tags["ZCUBE_ID"]
    assert not file_in_stable_zcube(f, ["id"], {cube: 10})
    assert file_in_stable_zcube(f, ["id"], {cube: DEFAULT_MIN_CUBE_SIZE})
    assert not file_in_stable_zcube(f, ["other"], {cube: DEFAULT_MIN_CUBE_SIZE})


# ---------------------------------------------------------------- backfill

def test_row_tracking_backfill(tmp_table_path):
    from delta_tpu.commands.backfill import backfill_row_tracking
    from delta_tpu.rowtracking import ROW_TRACKING_DOMAIN, current_high_watermark

    for i in range(3):
        _write(tmp_table_path, i * 10, 10)
    table = Table.for_path(tmp_table_path)
    snap = table.latest_snapshot()
    assert all(a.baseRowId is None for a in snap.state.add_files())

    m = backfill_row_tracking(table, batch_size=2)
    assert m.num_files_backfilled == 3
    assert m.num_batches == 2  # 2 + 1

    snap = Table.for_path(tmp_table_path).latest_snapshot()
    adds = snap.state.add_files()
    ids = sorted(a.baseRowId for a in adds)
    assert all(b is not None for b in ids)
    # ranges must not overlap (each file spans numRecords ids)
    assert len(set(ids)) == len(ids)
    assert snap.metadata.configuration.get("delta.enableRowTracking") == "true"
    assert "rowTracking" in (snap.protocol.writerFeatures or [])
    assert ROW_TRACKING_DOMAIN in snap.state.domain_metadata
    assert current_high_watermark(snap) >= 29
    # idempotent
    m2 = backfill_row_tracking(table)
    assert m2.num_files_backfilled == 0


# ---------------------------------------------------------------- deep clone

def test_deep_clone(tmp_path):
    from delta_tpu.commands.restore import clone

    src = str(tmp_path / "src")
    dst = str(tmp_path / "dst")
    _write(src, 0, 10)
    _write(src, 10, 10)
    src_table = Table.for_path(src)
    clone(src_table, dst, shallow=False)

    rows = dta.read_table(dst)
    assert sorted(rows.column("id").to_pylist()) == list(range(20))
    # deep clone is self-contained: paths are relative, files materialized
    snap = Table.for_path(dst).latest_snapshot()
    for a in snap.state.add_files():
        assert not a.path.startswith("/") and "://" not in a.path
    # destroying the source must not break the clone
    import shutil

    shutil.rmtree(src)
    assert sorted(dta.read_table(dst).column("id").to_pylist()) == list(range(20))


def test_deep_clone_copies_deletion_vectors(tmp_path):
    from delta_tpu.commands.dml import delete
    from delta_tpu.commands.restore import clone
    from delta_tpu.expressions.tree import col, lit

    src = str(tmp_path / "src")
    dst = str(tmp_path / "dst")
    _write(src, 0, 10)
    table = Table.for_path(src)
    from delta_tpu.commands.alter import set_properties

    set_properties(table, {"delta.enableDeletionVectors": "true"})
    delete(Table.for_path(src), predicate=col("id") < lit(3))
    snap = Table.for_path(src).latest_snapshot()
    assert any(a.deletionVector is not None for a in snap.state.add_files())

    clone(Table.for_path(src), dst, shallow=False)
    import shutil

    shutil.rmtree(src)
    rows = dta.read_table(dst)
    assert sorted(rows.column("id").to_pylist()) == list(range(3, 10))


# ------------------------------------------------------- schema tracking log

def test_schema_tracking_log(tmp_path):
    from delta_tpu.commands.alter import add_columns
    from delta_tpu.streaming import DeltaSource
    from delta_tpu.streaming.schema_log import (
        SchemaEvolutionRequiresRestart,
        SchemaTrackingLog,
    )
    from delta_tpu.engine.host import HostEngine

    path = str(tmp_path / "t")
    ckpt = str(tmp_path / "ckpt")
    _write(path, 0, 5)
    table = Table.for_path(path)
    engine = table.engine
    log = SchemaTrackingLog(engine, ckpt, table.latest_snapshot().metadata.id)

    src = DeltaSource(table, schema_tracking_log=log)
    off0 = src.latest_offset(None)
    assert src.get_batch(None, off0).num_rows == 5

    # mid-stream schema change + new data
    add_columns(table, [StructField("extra", STRING)])
    _write(path, 10, 5, {"extra": pa.array(["e"] * 5)})

    with pytest.raises(SchemaEvolutionRequiresRestart):
        off1 = src.latest_offset(off0)
        src.get_batch(off0, off1)
    assert log.latest() is not None

    # restarted stream adopts the evolved schema and continues
    src2 = DeltaSource(table, schema_tracking_log=log)
    off1 = src2.latest_offset(off0)
    batch = src2.get_batch(off0, off1)
    assert batch.num_rows == 5
    assert "extra" in src2.read_schema()


def test_schema_change_without_log_fails(tmp_path):
    from delta_tpu.commands.alter import add_columns
    from delta_tpu.streaming import DeltaSource

    path = str(tmp_path / "t")
    _write(path, 0, 5)
    table = Table.for_path(path)
    src = DeltaSource(table)
    off0 = src.latest_offset(None)
    src.get_batch(None, off0)

    add_columns(table, [StructField("extra", STRING)])
    _write(path, 10, 5, {"extra": pa.array(["e"] * 5)})
    with pytest.raises(DeltaError):
        off1 = src.latest_offset(off0)
        src.get_batch(off0, off1)

"""Pallas kernels vs their jnp/numpy references (interpret mode on CPU)."""

import jax.numpy as jnp
import numpy as np
import pytest

from delta_tpu.ops.pallas_kernels import (
    HAVE_PALLAS,
    batched_file_stats,
    interleave_bits_auto,
    interleave_bits_tiled,
)
from delta_tpu.ops.zorder import interleave_bits

pytestmark = pytest.mark.skipif(not HAVE_PALLAS, reason="pallas unavailable")


def test_interleave_tiled_matches_jnp():
    rng = np.random.default_rng(0)
    n = 2048
    cols = [rng.integers(0, 2**32, n, dtype=np.uint32) for _ in range(3)]
    ref = np.asarray(interleave_bits([jnp.asarray(c) for c in cols]))
    got = np.asarray(interleave_bits_tiled(jnp.stack([jnp.asarray(c) for c in cols])))
    np.testing.assert_array_equal(got, ref)


def test_interleave_auto_fallback_on_ragged():
    rng = np.random.default_rng(1)
    n = 1000  # not a tile multiple -> fallback path
    cols = [rng.integers(0, 2**32, n, dtype=np.uint32) for _ in range(2)]
    ref = np.asarray(interleave_bits([jnp.asarray(c) for c in cols]))
    got = np.asarray(interleave_bits_auto([jnp.asarray(c) for c in cols]))
    np.testing.assert_array_equal(got, ref)


def test_segmented_minmax():
    rng = np.random.default_rng(2)
    f, r = 10, 300
    values = rng.normal(size=(f, r)).astype(np.float32)
    valid = rng.random((f, r)) < 0.9
    valid[3] = False  # one all-null file
    mn, mx, null_count, num_records = batched_file_stats(values, valid)
    for i in range(f):
        sel = values[i][valid[i]]
        if sel.size:
            assert mn[i] == pytest.approx(sel.min())
            assert mx[i] == pytest.approx(sel.max())
        else:
            assert np.isinf(mn[i])
        assert null_count[i] == r - valid[i].sum()
        assert num_records[i] == r

import threading

import pytest

from delta_tpu.storage.logstore import (
    FaultInjectingLogStore,
    InMemoryLogStore,
    LocalLogStore,
    logstore_for_path,
)


@pytest.fixture(params=["local", "memory"])
def store_and_root(request, tmp_path):
    if request.param == "local":
        return LocalLogStore(), str(tmp_path)
    return InMemoryLogStore(), "memory://ns/root"


def test_put_if_absent(store_and_root):
    store, root = store_and_root
    p = f"{root}/d/file.json"
    store.write(p, b"one")
    assert store.read(p) == b"one"
    with pytest.raises(FileExistsError):
        store.write(p, b"two")
    assert store.read(p) == b"one"
    store.write(p, b"three", overwrite=True)
    assert store.read(p) == b"three"


def test_put_if_absent_race(store_and_root):
    """Exactly one of N concurrent writers must win."""
    store, root = store_and_root
    p = f"{root}/race/commit.json"
    wins, errs = [], []
    barrier = threading.Barrier(8)

    def attempt(i):
        barrier.wait()
        try:
            store.write(p, f"writer-{i}".encode())
            wins.append(i)
        except FileExistsError:
            errs.append(i)

    threads = [threading.Thread(target=attempt, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(wins) == 1
    assert len(errs) == 7
    assert store.read(p) == f"writer-{wins[0]}".encode()


def test_list_from_ordering(store_and_root):
    store, root = store_and_root
    names = ["00000000000000000002.json", "00000000000000000010.json",
             "00000000000000000001.json"]
    for n in names:
        store.write(f"{root}/log/{n}", b"x")
    listed = [f.path.rsplit("/", 1)[-1] for f in store.list_from(f"{root}/log/00000000000000000002.json")]
    assert listed == ["00000000000000000002.json", "00000000000000000010.json"]


def test_list_from_missing_parent(store_and_root):
    store, root = store_and_root
    with pytest.raises(FileNotFoundError):
        list(store.list_from(f"{root}/nope/x"))


def test_fault_injection():
    inner = InMemoryLogStore()
    store = FaultInjectingLogStore(inner)
    store.fail_writes(lambda p: p.endswith("1.json"), once=True)
    with pytest.raises(IOError):
        store.write("memory://x/1.json", b"a")
    store.write("memory://x/1.json", b"a")  # once=True: second attempt fine
    assert store.write_log.count("memory://x/1.json") == 2


def test_scheme_resolution(tmp_path):
    assert isinstance(logstore_for_path(str(tmp_path / "f")), LocalLogStore)
    m1 = logstore_for_path("memory://a/x")
    m2 = logstore_for_path("memory://a/y")
    m3 = logstore_for_path("memory://b/x")
    assert m1 is m2 and m1 is not m3

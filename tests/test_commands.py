"""OPTIMIZE / VACUUM / DELETE / UPDATE command tests."""

import os
import time

import numpy as np
import pyarrow as pa
import pytest

import delta_tpu.api as dta
from delta_tpu.commands.dml import delete, update
from delta_tpu.commands.vacuum import vacuum
from delta_tpu.expressions import col, lit
from delta_tpu.table import Table


def _mk_table(path, n=500, n_commits=5, partition=False, props=None):
    rng = np.random.default_rng(1)
    for i in range(n_commits):
        data = pa.table(
            {
                "id": pa.array(np.arange(i * n, (i + 1) * n, dtype=np.int64)),
                "x": pa.array(rng.normal(size=n)),
                "y": pa.array(rng.integers(0, 1000, n).astype(np.int64)),
                "cat": pa.array([f"c{j % 3}" for j in range(n)]),
            }
        )
        dta.write_table(
            path, data,
            partition_by=["cat"] if (partition and i == 0) else None,
            properties=props if i == 0 else None,
        )
    return Table.for_path(path)


def test_optimize_compaction(tmp_table_path):
    table = _mk_table(tmp_table_path, n=200, n_commits=6)
    before = table.latest_snapshot()
    assert before.num_files == 6
    m = table.optimize().execute_compaction()
    assert m.num_files_removed == 6
    assert m.num_files_added == 1
    after = table.latest_snapshot()
    assert after.num_files == 1
    out = dta.read_table(tmp_table_path)
    assert out.num_rows == 1200
    assert sorted(out.column("id").to_pylist()) == list(range(1200))


def test_optimize_compaction_partitioned(tmp_table_path):
    table = _mk_table(tmp_table_path, n=90, n_commits=4, partition=True)
    m = table.optimize().execute_compaction()
    after = table.latest_snapshot()
    # one compacted file per partition
    assert after.num_files == 3
    assert m.partitions_optimized == 3
    out = dta.read_table(tmp_table_path)
    assert out.num_rows == 360


def test_optimize_zorder(tmp_table_path):
    table = _mk_table(tmp_table_path, n=300, n_commits=3)
    m = table.optimize().execute_zorder_by("x", "y")
    assert m.num_files_removed == 3
    assert m.num_files_added >= 1
    out = dta.read_table(tmp_table_path)
    assert out.num_rows == 900
    # data intact
    assert sorted(out.column("id").to_pylist()) == list(range(900))


def test_optimize_hilbert(tmp_table_path):
    table = _mk_table(tmp_table_path, n=200, n_commits=2)
    m = table.optimize().execute_zorder_by("x", "y", curve="hilbert")
    out = dta.read_table(tmp_table_path)
    assert out.num_rows == 400


def test_delete_full_files(tmp_table_path):
    table = _mk_table(tmp_table_path, n=100, n_commits=3)
    m = delete(table)  # unconditional
    assert m.num_files_removed_fully == 3
    out = dta.read_table(tmp_table_path)
    assert out.num_rows == 0


def test_delete_predicate_rewrite(tmp_table_path):
    table = _mk_table(tmp_table_path, n=100, n_commits=2)
    m = delete(table, col("id") < lit(50))
    assert m.num_rows_deleted == 50
    out = dta.read_table(tmp_table_path)
    assert out.num_rows == 150
    assert min(out.column("id").to_pylist()) == 50


def test_delete_with_deletion_vectors(tmp_table_path):
    table = _mk_table(
        tmp_table_path, n=100, n_commits=1,
        props={"delta.enableDeletionVectors": "true"},
    )
    m = delete(table, col("id") < lit(30))
    assert m.num_dvs_written == 1
    snap = table.latest_snapshot()
    files = snap.state.add_files()
    assert len(files) == 1 and files[0].deletionVector is not None
    assert files[0].deletionVector.cardinality == 30
    out = dta.read_table(tmp_table_path)
    assert out.num_rows == 70
    assert min(out.column("id").to_pylist()) == 30
    # second delete on the same file merges DVs
    m2 = delete(table, col("id") < lit(40))
    out2 = dta.read_table(tmp_table_path)
    assert out2.num_rows == 60


def test_update(tmp_table_path):
    table = _mk_table(tmp_table_path, n=100, n_commits=1)
    m = update(table, {"y": lit(-1)}, col("id") < lit(10))
    assert m.num_rows_updated == 10
    out = dta.read_table(tmp_table_path).sort_by("id")
    ys = out.column("y").to_pylist()
    assert all(v == -1 for v in ys[:10])
    assert all(v != -1 for v in ys[10:20]) or True
    assert out.num_rows == 100


def test_update_with_expression(tmp_table_path):
    table = _mk_table(tmp_table_path, n=50, n_commits=1)
    update(table, {"y": col("id")}, col("id") >= lit(25))
    out = dta.read_table(tmp_table_path).sort_by("id")
    ys = out.column("y").to_pylist()
    ids = out.column("id").to_pylist()
    for i, y in zip(ids[25:], ys[25:]):
        assert y == i


def test_vacuum(tmp_table_path):
    table = _mk_table(tmp_table_path, n=100, n_commits=2)
    delete(table, col("id") < lit(100))  # drops the first file entirely
    res_dry = vacuum(table, retention_hours=0, dry_run=True)
    assert res_dry.num_deleted == 1
    # file still exists
    assert all(
        os.path.exists(os.path.join(tmp_table_path, f)) for f in res_dry.files_deleted
    )
    res = vacuum(table, retention_hours=0)
    assert sorted(res.files_deleted) == sorted(res_dry.files_deleted)
    for f in res.files_deleted:
        assert not os.path.exists(os.path.join(tmp_table_path, f))
    # table still reads fine
    out = dta.read_table(tmp_table_path)
    assert out.num_rows == 100


def test_vacuum_protects_recent_tombstones(tmp_table_path):
    table = _mk_table(tmp_table_path, n=50, n_commits=2)
    delete(table, col("id") < lit(50))
    res = vacuum(table, retention_hours=1000, dry_run=False)
    assert res.num_deleted == 0


def test_cdc_files_written(tmp_table_path):
    table = _mk_table(
        tmp_table_path, n=60, n_commits=1,
        props={"delta.enableChangeDataFeed": "true"},
    )
    delete(table, col("id") < lit(10))
    cdc_dir = os.path.join(tmp_table_path, "_change_data")
    assert os.path.isdir(cdc_dir)
    assert len(os.listdir(cdc_dir)) == 1


def test_vacuum_with_inventory(tmp_table_path):
    """VacuumCommand.scala:59 USING INVENTORY role: a pre-computed
    file inventory replaces the recursive listing."""
    import pyarrow as pa

    table = _mk_table(tmp_table_path, n=100, n_commits=2)
    delete(table, col("id") < lit(100))
    listed = vacuum(table, retention_hours=0, dry_run=True)
    assert listed.num_deleted == 1

    # inventory covering the whole table dir (absolute paths)
    rows = []
    for root, _, files in os.walk(tmp_table_path):
        for f in files:
            p = os.path.join(root, f)
            rows.append((p, os.path.getsize(p), False,
                         int(os.stat(p).st_mtime * 1000)))
    inv = pa.table({
        "path": pa.array([r[0] for r in rows]),
        "length": pa.array([r[1] for r in rows], pa.int64()),
        "isDir": pa.array([r[2] for r in rows]),
        "modificationTime": pa.array([r[3] for r in rows], pa.int64()),
    })
    res = vacuum(table, retention_hours=0, dry_run=True, inventory=inv)
    assert sorted(res.files_deleted) == sorted(listed.files_deleted)

    # a partial inventory deletes only what it covers
    doomed_rel = listed.files_deleted[0]
    partial = inv.filter(pa.compute.invert(pa.compute.match_substring(
        inv.column("path"), doomed_rel)))
    res2 = vacuum(table, retention_hours=0, dry_run=True,
                  inventory=partial)
    assert res2.num_deleted == 0

    # _delta_log rows in the inventory are never candidates
    res3 = vacuum(table, retention_hours=0, inventory=inv)
    assert sorted(res3.files_deleted) == sorted(listed.files_deleted)
    assert os.path.isdir(os.path.join(tmp_table_path, "_delta_log"))
    out = dta.read_table(tmp_table_path)
    assert out.num_rows == 100


def test_vacuum_inventory_schema_validated(tmp_table_path):
    import pyarrow as pa
    import pytest

    from delta_tpu.errors import DeltaError

    table = _mk_table(tmp_table_path, n=10, n_commits=1)
    bad = pa.table({"path": pa.array(["x"]),
                    "length": pa.array([1], pa.int64())})
    with pytest.raises(DeltaError, match="inventory schema"):
        vacuum(table, retention_hours=0, inventory=bad)


def test_vacuum_inventory_pandas_frame(tmp_table_path):
    import pandas as pd

    table = _mk_table(tmp_table_path, n=100, n_commits=2)
    delete(table, col("id") < lit(100))
    listed = vacuum(table, retention_hours=0, dry_run=True)
    inv = pd.DataFrame({
        "path": listed.files_deleted,  # table-relative paths
        "length": [1] * len(listed.files_deleted),
        "isDir": [False] * len(listed.files_deleted),
        "modificationTime": [0] * len(listed.files_deleted),
    })
    res = vacuum(table, retention_hours=0, dry_run=True, inventory=inv)
    assert sorted(res.files_deleted) == sorted(listed.files_deleted)


def test_vacuum_inventory_rejects_path_traversal(tmp_table_path, tmp_path):
    """'..' segments must neither escape the table root nor alias a
    live file past the protected-set check."""
    import pyarrow as pa

    table = _mk_table(tmp_table_path, n=100, n_commits=1)
    victim = tmp_path / "outside.txt"
    victim.write_text("precious")
    os.utime(victim, (0, 0))
    live = dta.read_table(tmp_table_path)  # table intact before
    live_file = [f for f in os.listdir(tmp_table_path)
                 if f.endswith(".parquet")][0]
    inv = pa.table({
        "path": pa.array([
            f"{tmp_table_path}/data/../../{victim.name}",
            f"{tmp_table_path}/x/../{live_file}",  # alias of live file
            "sub/../../../etc/hosts",
        ]),
        "length": pa.array([1, 1, 1], pa.int64()),
        "isDir": pa.array([False, False, False]),
        "modificationTime": pa.array([0, 0, 0], pa.int64()),
    })
    res = vacuum(table, retention_hours=0, inventory=inv)
    assert res.num_deleted == 0
    assert victim.exists()
    assert os.path.exists(os.path.join(tmp_table_path, live_file))
    assert dta.read_table(tmp_table_path).num_rows == live.num_rows


def test_vacuum_inventory_null_mtime_is_skipped(tmp_table_path):
    import pyarrow as pa

    table = _mk_table(tmp_table_path, n=100, n_commits=2)
    delete(table, col("id") < lit(100))
    listed = vacuum(table, retention_hours=0, dry_run=True)
    inv = pa.table({
        "path": pa.array(listed.files_deleted),
        "length": pa.array([1] * len(listed.files_deleted), pa.int64()),
        "isDir": pa.array([False] * len(listed.files_deleted)),
        "modificationTime": pa.array([None] * len(listed.files_deleted),
                                     pa.int64()),
    })
    res = vacuum(table, retention_hours=0, dry_run=True, inventory=inv)
    assert res.num_deleted == 0  # unknown age: conservative skip


# ---- VACUUM LITE (`VacuumCommand.scala:281-636`) ---------------------


def test_vacuum_lite_deletes_tombstones_not_untracked(tmp_table_path):
    """LITE candidates come from the log's RemoveFile tombstones, so an
    untracked file survives (FULL's listing would delete it) — the
    defining behavioral difference (`VacuumCommand.scala:506`)."""
    table = _mk_table(tmp_table_path, n=100, n_commits=2)
    delete(table, col("id") < lit(100))  # tombstones the first file
    junk = os.path.join(tmp_table_path, "untracked-junk.parquet")
    with open(junk, "wb") as f:
        f.write(b"not a real parquet")
    os.utime(junk, (0, 0))  # old enough that FULL would delete it
    res = vacuum(table, retention_hours=0, vacuum_type="LITE")
    assert res.type_of_vacuum == "LITE"
    assert res.num_deleted == 1
    assert not os.path.exists(
        os.path.join(tmp_table_path, res.files_deleted[0]))
    assert os.path.exists(junk)  # untracked: invisible to LITE
    assert res.eligible_start_commit_version == 0
    assert res.eligible_end_commit_version == table.latest_snapshot().version
    # watermark persisted for the next incremental run
    info = os.path.join(tmp_table_path, "_delta_log", "_last_vacuum_info")
    assert os.path.exists(info)
    import json as _json

    mark = _json.load(open(info))
    assert mark["latestCommitVersionOutsideOfRetentionWindow"] == \
        res.eligible_end_commit_version
    # FULL still reaps the junk afterwards, and (having observed every
    # file) keeps the watermark current rather than resetting it
    res_full = vacuum(table, retention_hours=0)
    assert "untracked-junk.parquet" in res_full.files_deleted
    assert _json.load(open(info))[
        "latestCommitVersionOutsideOfRetentionWindow"] == \
        res.eligible_end_commit_version


def test_vacuum_lite_incremental_watermark(tmp_table_path):
    """A second LITE run resumes after the first one's watermark
    (`VacuumCommand.scala:540-544`) and still finds new tombstones."""
    table = _mk_table(tmp_table_path, n=100, n_commits=2)
    delete(table, col("id") < lit(100))
    res1 = vacuum(table, retention_hours=0, vacuum_type="LITE")
    assert res1.num_deleted == 1
    delete(table, col("id") >= lit(100))
    res2 = vacuum(table, retention_hours=0, vacuum_type="LITE")
    assert res2.eligible_start_commit_version == \
        res1.eligible_end_commit_version + 1
    assert res2.num_deleted == 1
    # every data file is gone; the log still replays
    assert dta.read_table(tmp_table_path).num_rows == 0


def test_vacuum_lite_protects_recent_tombstones(tmp_table_path):
    table = _mk_table(tmp_table_path, n=50, n_commits=2)
    delete(table, col("id") < lit(50))
    res = vacuum(table, retention_hours=1000, vacuum_type="LITE")
    assert res.num_deleted == 0


def test_vacuum_lite_raises_after_unobserved_log_cleanup(tmp_table_path):
    """Commits expired before any vacuum observed them: their
    tombstones are unrecoverable from the log, so LITE must refuse
    (`VacuumCommand.scala:532-537` -> DELTA_CANNOT_VACUUM_LITE)."""
    from delta_tpu.errors import VacuumLiteError

    table = _mk_table(tmp_table_path, n=50, n_commits=3)
    table.checkpoint()
    # simulate metadata cleanup having expired the earliest commits
    for v in (0, 1):
        os.unlink(os.path.join(
            tmp_table_path, "_delta_log", f"{v:020d}.json"))
    table = Table.for_path(tmp_table_path)
    with pytest.raises(VacuumLiteError) as ei:
        vacuum(table, retention_hours=0, vacuum_type="LITE")
    assert ei.value.error_class == "DELTA_CANNOT_VACUUM_LITE"


def test_vacuum_lite_sql_surface(tmp_table_path):
    from delta_tpu.sql import sql

    table = _mk_table(tmp_table_path, n=60, n_commits=2)
    delete(table, col("id") < lit(60))
    res = sql(f"VACUUM '{tmp_table_path}' RETAIN 0 HOURS LITE DRY RUN")
    assert res.type_of_vacuum == "LITE" and res.dry_run
    assert res.num_deleted == 1
    assert os.path.exists(
        os.path.join(tmp_table_path, res.files_deleted[0]))


def test_vacuum_lite_rejects_inventory(tmp_table_path):
    from delta_tpu.errors import InvalidArgumentError

    table = _mk_table(tmp_table_path, n=10, n_commits=1)
    inv = pa.table({"path": ["x"], "length": [1], "isDir": [False],
                    "modificationTime": [0]})
    with pytest.raises(InvalidArgumentError):
        vacuum(table, retention_hours=0, inventory=inv,
               vacuum_type="LITE")


def test_vacuum_lite_empty_run_keeps_watermark(tmp_table_path):
    """An empty LITE run (nothing outside retention) must not reset or
    regress the watermark — that would rescan or spuriously trip the
    gap check after log cleanup."""
    import json as _json

    table = _mk_table(tmp_table_path, n=50, n_commits=2)
    delete(table, col("id") < lit(50))
    res1 = vacuum(table, retention_hours=0, vacuum_type="LITE")
    info = os.path.join(tmp_table_path, "_delta_log", "_last_vacuum_info")
    mark1 = _json.load(open(info))
    assert mark1["latestCommitVersionOutsideOfRetentionWindow"] == \
        res1.eligible_end_commit_version
    # big retention: cutoff predates every commit -> empty run
    res2 = vacuum(table, retention_hours=100000, vacuum_type="LITE")
    assert res2.num_deleted == 0
    assert _json.load(open(info)) == mark1  # unchanged


def test_vacuum_lite_contiguous_watermark_after_cleanup(tmp_table_path):
    """last_mark+1 == earliest is NOT a gap: every expired commit was
    scanned, so the next LITE run proceeds."""
    import json as _json

    table = _mk_table(tmp_table_path, n=50, n_commits=3)
    delete(table, col("id") < lit(50))  # version 3
    res1 = vacuum(table, retention_hours=0, vacuum_type="LITE")
    end1 = res1.eligible_end_commit_version
    table.checkpoint()
    # cleanup expires exactly the scanned prefix [0, end1]
    for v in range(0, end1 + 1):
        os.unlink(os.path.join(
            tmp_table_path, "_delta_log", f"{v:020d}.json"))
    table = Table.for_path(tmp_table_path)
    delete(table, col("id") >= lit(100))
    res2 = vacuum(table, retention_hours=0, vacuum_type="LITE")
    assert res2.eligible_start_commit_version == end1 + 1
    assert res2.num_deleted >= 1


def test_vacuum_lite_rejects_traversal_paths(tmp_table_path, tmp_path):
    """A logged remove path with '..' or an encoded absolute path must
    not unlink outside the table root (same guard as the inventory
    path)."""
    import json as _json

    victim = tmp_path / "victim.bin"
    victim.write_bytes(b"precious")
    table = _mk_table(tmp_table_path, n=10, n_commits=1)
    # hand-craft a commit with hostile remove paths
    rel_victim = os.path.relpath(str(victim), tmp_table_path)
    log = os.path.join(tmp_table_path, "_delta_log")
    evil = [
        {"remove": {"path": rel_victim.replace(os.sep, "/"),
                    "deletionTimestamp": 1, "dataChange": True}},
        {"remove": {"path": "%2Fetc%2Fhostname",
                    "deletionTimestamp": 1, "dataChange": True}},
    ]
    with open(os.path.join(log, f"{1:020d}.json"), "w") as f:
        f.write("\n".join(_json.dumps(a) for a in evil))
    table = Table.for_path(tmp_table_path)
    res = vacuum(table, retention_hours=0, vacuum_type="LITE")
    assert victim.exists()
    assert all("victim" not in p and "etc" not in p
               for p in res.files_deleted)


def test_vacuum_lite_repeat_is_empty(tmp_table_path):
    """Running LITE twice with no new commits must not re-report (or
    re-'delete') the files the first run already removed."""
    table = _mk_table(tmp_table_path, n=50, n_commits=2)
    delete(table, col("id") < lit(50))
    res1 = vacuum(table, retention_hours=0, vacuum_type="LITE")
    assert res1.num_deleted == 1
    res2 = vacuum(table, retention_hours=0, vacuum_type="LITE")
    assert res2.num_deleted == 0
    res_dry = vacuum(table, retention_hours=0, vacuum_type="LITE",
                     dry_run=True)
    assert res_dry.num_deleted == 0


def test_vacuum_full_enables_lite_on_cleaned_log(tmp_table_path):
    """A FULL vacuum observes every file, so on a table whose log head
    was cleaned up it advances the watermark and un-wedges LITE."""
    table = _mk_table(tmp_table_path, n=50, n_commits=3)
    table.checkpoint()
    for v in (0, 1):
        os.unlink(os.path.join(
            tmp_table_path, "_delta_log", f"{v:020d}.json"))
    table = Table.for_path(tmp_table_path)
    from delta_tpu.errors import VacuumLiteError

    with pytest.raises(VacuumLiteError):
        vacuum(table, retention_hours=0, vacuum_type="LITE")
    vacuum(table, retention_hours=0)  # FULL
    delete(table, col("id") < lit(50))
    res = vacuum(table, retention_hours=0, vacuum_type="LITE")
    assert res.num_deleted == 1


def test_vacuum_sql_modifier_order(tmp_table_path):
    """Reference grammar (`DeltaSqlBase.g4:198`) accepts modifiers in
    any order: LITE before RETAIN must parse too."""
    from delta_tpu.sql import sql

    table = _mk_table(tmp_table_path, n=60, n_commits=2)
    delete(table, col("id") < lit(60))
    res = sql(f"VACUUM '{tmp_table_path}' LITE RETAIN 0 HOURS DRY RUN")
    assert res.type_of_vacuum == "LITE" and res.dry_run
    assert res.num_deleted == 1
    res2 = sql(f"VACUUM '{tmp_table_path}' DRY RUN")
    assert res2.type_of_vacuum == "FULL" and res2.dry_run

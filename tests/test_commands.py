"""OPTIMIZE / VACUUM / DELETE / UPDATE command tests."""

import os
import time

import numpy as np
import pyarrow as pa
import pytest

import delta_tpu.api as dta
from delta_tpu.commands.dml import delete, update
from delta_tpu.commands.vacuum import vacuum
from delta_tpu.expressions import col, lit
from delta_tpu.table import Table


def _mk_table(path, n=500, n_commits=5, partition=False, props=None):
    rng = np.random.default_rng(1)
    for i in range(n_commits):
        data = pa.table(
            {
                "id": pa.array(np.arange(i * n, (i + 1) * n, dtype=np.int64)),
                "x": pa.array(rng.normal(size=n)),
                "y": pa.array(rng.integers(0, 1000, n).astype(np.int64)),
                "cat": pa.array([f"c{j % 3}" for j in range(n)]),
            }
        )
        dta.write_table(
            path, data,
            partition_by=["cat"] if (partition and i == 0) else None,
            properties=props if i == 0 else None,
        )
    return Table.for_path(path)


def test_optimize_compaction(tmp_table_path):
    table = _mk_table(tmp_table_path, n=200, n_commits=6)
    before = table.latest_snapshot()
    assert before.num_files == 6
    m = table.optimize().execute_compaction()
    assert m.num_files_removed == 6
    assert m.num_files_added == 1
    after = table.latest_snapshot()
    assert after.num_files == 1
    out = dta.read_table(tmp_table_path)
    assert out.num_rows == 1200
    assert sorted(out.column("id").to_pylist()) == list(range(1200))


def test_optimize_compaction_partitioned(tmp_table_path):
    table = _mk_table(tmp_table_path, n=90, n_commits=4, partition=True)
    m = table.optimize().execute_compaction()
    after = table.latest_snapshot()
    # one compacted file per partition
    assert after.num_files == 3
    assert m.partitions_optimized == 3
    out = dta.read_table(tmp_table_path)
    assert out.num_rows == 360


def test_optimize_zorder(tmp_table_path):
    table = _mk_table(tmp_table_path, n=300, n_commits=3)
    m = table.optimize().execute_zorder_by("x", "y")
    assert m.num_files_removed == 3
    assert m.num_files_added >= 1
    out = dta.read_table(tmp_table_path)
    assert out.num_rows == 900
    # data intact
    assert sorted(out.column("id").to_pylist()) == list(range(900))


def test_optimize_hilbert(tmp_table_path):
    table = _mk_table(tmp_table_path, n=200, n_commits=2)
    m = table.optimize().execute_zorder_by("x", "y", curve="hilbert")
    out = dta.read_table(tmp_table_path)
    assert out.num_rows == 400


def test_delete_full_files(tmp_table_path):
    table = _mk_table(tmp_table_path, n=100, n_commits=3)
    m = delete(table)  # unconditional
    assert m.num_files_removed_fully == 3
    out = dta.read_table(tmp_table_path)
    assert out.num_rows == 0


def test_delete_predicate_rewrite(tmp_table_path):
    table = _mk_table(tmp_table_path, n=100, n_commits=2)
    m = delete(table, col("id") < lit(50))
    assert m.num_rows_deleted == 50
    out = dta.read_table(tmp_table_path)
    assert out.num_rows == 150
    assert min(out.column("id").to_pylist()) == 50


def test_delete_with_deletion_vectors(tmp_table_path):
    table = _mk_table(
        tmp_table_path, n=100, n_commits=1,
        props={"delta.enableDeletionVectors": "true"},
    )
    m = delete(table, col("id") < lit(30))
    assert m.num_dvs_written == 1
    snap = table.latest_snapshot()
    files = snap.state.add_files()
    assert len(files) == 1 and files[0].deletionVector is not None
    assert files[0].deletionVector.cardinality == 30
    out = dta.read_table(tmp_table_path)
    assert out.num_rows == 70
    assert min(out.column("id").to_pylist()) == 30
    # second delete on the same file merges DVs
    m2 = delete(table, col("id") < lit(40))
    out2 = dta.read_table(tmp_table_path)
    assert out2.num_rows == 60


def test_update(tmp_table_path):
    table = _mk_table(tmp_table_path, n=100, n_commits=1)
    m = update(table, {"y": lit(-1)}, col("id") < lit(10))
    assert m.num_rows_updated == 10
    out = dta.read_table(tmp_table_path).sort_by("id")
    ys = out.column("y").to_pylist()
    assert all(v == -1 for v in ys[:10])
    assert all(v != -1 for v in ys[10:20]) or True
    assert out.num_rows == 100


def test_update_with_expression(tmp_table_path):
    table = _mk_table(tmp_table_path, n=50, n_commits=1)
    update(table, {"y": col("id")}, col("id") >= lit(25))
    out = dta.read_table(tmp_table_path).sort_by("id")
    ys = out.column("y").to_pylist()
    ids = out.column("id").to_pylist()
    for i, y in zip(ids[25:], ys[25:]):
        assert y == i


def test_vacuum(tmp_table_path):
    table = _mk_table(tmp_table_path, n=100, n_commits=2)
    delete(table, col("id") < lit(100))  # drops the first file entirely
    res_dry = vacuum(table, retention_hours=0, dry_run=True)
    assert res_dry.num_deleted == 1
    # file still exists
    assert all(
        os.path.exists(os.path.join(tmp_table_path, f)) for f in res_dry.files_deleted
    )
    res = vacuum(table, retention_hours=0)
    assert sorted(res.files_deleted) == sorted(res_dry.files_deleted)
    for f in res.files_deleted:
        assert not os.path.exists(os.path.join(tmp_table_path, f))
    # table still reads fine
    out = dta.read_table(tmp_table_path)
    assert out.num_rows == 100


def test_vacuum_protects_recent_tombstones(tmp_table_path):
    table = _mk_table(tmp_table_path, n=50, n_commits=2)
    delete(table, col("id") < lit(50))
    res = vacuum(table, retention_hours=1000, dry_run=False)
    assert res.num_deleted == 0


def test_cdc_files_written(tmp_table_path):
    table = _mk_table(
        tmp_table_path, n=60, n_commits=1,
        props={"delta.enableChangeDataFeed": "true"},
    )
    delete(table, col("id") < lit(10))
    cdc_dir = os.path.join(tmp_table_path, "_change_data")
    assert os.path.isdir(cdc_dir)
    assert len(os.listdir(cdc_dir)) == 1

"""Error-class catalog: every concrete error type resolves to a stable
class with an SQLSTATE (the reference's delta-error-classes.json role)."""

import inspect

import delta_tpu.errors as E
from delta_tpu.errors import DeltaError, error_catalog, error_info


def _concrete_error_classes():
    out = []
    for _, obj in inspect.getmembers(E, inspect.isclass):
        if issubclass(obj, DeltaError):
            out.append(obj)
    # classes defined elsewhere that carry their own error_class
    from delta_tpu.commands.merge import MergeCardinalityError
    from delta_tpu.log.segment import CorruptLogError

    out += [MergeCardinalityError, CorruptLogError]
    return out


def test_every_error_class_is_in_the_catalog():
    catalog = error_catalog()
    for cls in _concrete_error_classes():
        assert cls.error_class in catalog, cls.__name__
        entry = catalog[cls.error_class]
        assert entry["sqlState"]
        assert entry["message"]


def test_error_classes_are_unique_where_distinct():
    seen = {}
    for cls in _concrete_error_classes():
        if cls.error_class in seen and seen[cls.error_class] is not cls:
            # subclass sharing a parent's class is allowed only for
            # aliases; distinct top-level types must not collide
            assert issubclass(cls, seen[cls.error_class]) or issubclass(
                seen[cls.error_class], cls), (
                f"{cls.__name__} and {seen[cls.error_class].__name__} share "
                f"{cls.error_class}")
        seen.setdefault(cls.error_class, cls)


def test_error_info_structure():
    try:
        raise E.VersionNotFoundError(version=7, earliest=0, latest=3)
    except DeltaError as e:
        info = error_info(e)
    assert info["errorClass"] == "DELTA_VERSION_NOT_FOUND"
    assert info["sqlState"] == "42815"
    assert info["parameters"]["version"] == 7
    assert "version" in info["messageTemplate"]


# ---- package walk: every raise site is typed + cataloged (r4) --------

import ast
import os

PKG = os.path.dirname(E.__file__)

# exceptions that are NOT user-facing Delta errors: builtins for
# internal invariants, storage-protocol exceptions with documented
# contracts, and parse-layer locals
_ALLOWED_NON_DELTA = {
    "ValueError", "TypeError", "KeyError", "RuntimeError", "IOError",
    "OSError", "FileNotFoundError", "FileExistsError",
    "NotImplementedError", "StopIteration", "TimeoutError",
    "AssertionError", "ConnectionError", "InterruptedError",
    "FileAlreadyExistsError", "PreconditionFailedError",
    "TableAlreadyExistsError", "TableNotInCatalogError",
    "ParseError", "CommitFailedException",
    # internal fall-back signal of the page decoder: always caught,
    # the Arrow reader takes over (log/page_decode.py)
    "DecodeUnsupported",
    # storage-protocol error carrying the DynamoDB __type; the arbiter
    # maps the arbitration-relevant case (ConditionalCheckFailed) to
    # FileAlreadyExistsError like the other store clients
    "DynamoDbError",
    # storage-protocol IOError subclasses: StorageRequestError carries
    # the HTTP status the resilience classifier keys on; ChaosError is
    # the chaos harness's injected (always-transient) fault
    "StorageRequestError", "ChaosError",
    # device-chaos twins: seeded injections at the dispatch funnel,
    # classified by retryable/markers like real runtime errors
    # (resilience/device_chaos.py)
    "DeviceChaosError", "DeviceResourceExhaustedError",
}


def _raise_sites():
    for root, _dirs, files in os.walk(PKG):
        for f in files:
            if not f.endswith(".py"):
                continue
            path = os.path.join(root, f)
            tree = ast.parse(open(path).read(), filename=path)
            for node in ast.walk(tree):
                if not isinstance(node, ast.Raise) or node.exc is None:
                    continue
                exc = node.exc
                if isinstance(exc, ast.Call):
                    exc = exc.func
                if isinstance(exc, ast.Name):
                    yield path, node.lineno, exc.id
                elif isinstance(exc, ast.Attribute):
                    yield path, node.lineno, exc.attr


def test_no_generic_delta_error_raises():
    """All 204 former `raise DeltaError(...)` sites were mapped to
    typed classes in round 4; this pins the count at zero."""
    generic = [f"{os.path.relpath(p, PKG)}:{ln}"
               for p, ln, name in _raise_sites() if name == "DeltaError"]
    assert not generic, (
        f"raise a typed, cataloged subclass instead: {generic}")


def test_every_raise_site_is_typed_or_allowed():
    known = {n for n, obj in inspect.getmembers(E, inspect.isclass)
             if issubclass(obj, DeltaError)}
    # typed DeltaError subclasses defined next to their subsystem
    known |= {"MergeCardinalityError", "CorruptLogError",
              "RemoteDeltaError", "PostCommitHookError",
              "SchemaEvolutionRequiresRestart", "CheckpointWriteError"}
    extra_builtin = {"AttributeError", "EOFError", "SystemExit"}
    bad = []
    for p, ln, name in _raise_sites():
        if name in known or name in _ALLOWED_NON_DELTA \
                or name in extra_builtin:
            continue
        if name.startswith("_"):
            continue  # module-internal control-flow exceptions
        if name[0].islower() or name in ("e", "err", "exc"):
            continue  # re-raise of a caught local
        bad.append(f"{os.path.relpath(p, PKG)}:{ln}: {name}")
    assert not bad, f"unclassified raise sites: {bad}"


def test_catalog_round5_floor():
    # reference catalog is ~448 classes; round 5 target was >=200
    assert len(error_catalog()) >= 200


# ---- raisability census: no dead catalog entries (r5) ----------------

def _class_defaults():
    """class name -> default error_class, from every ClassDef in the
    package (AST, so subsystem-local classes count too)."""
    out = {}
    for root, _dirs, files in os.walk(PKG):
        for f in files:
            if not f.endswith(".py"):
                continue
            tree = ast.parse(open(os.path.join(root, f)).read())
            for node in ast.walk(tree):
                if not isinstance(node, ast.ClassDef):
                    continue
                for st in node.body:
                    if isinstance(st, ast.Assign):
                        for tg in st.targets:
                            if isinstance(tg, ast.Name) \
                                    and tg.id == "error_class" \
                                    and isinstance(st.value, ast.Constant):
                                out[node.name] = st.value.value
    return out


def _produced_classes():
    """Error classes some raise site actually produces: an explicit
    error_class= kwarg, or the raised type's default."""
    defaults = _class_defaults()
    produced = set()
    raised_types = set()
    for root, _dirs, files in os.walk(PKG):
        for f in files:
            if not f.endswith(".py"):
                continue
            tree = ast.parse(open(os.path.join(root, f)).read())
            for node in ast.walk(tree):
                if not (isinstance(node, ast.Raise)
                        and isinstance(node.exc, ast.Call)):
                    continue
                call = node.exc
                ec = next((kw.value.value for kw in call.keywords
                           if kw.arg == "error_class"
                           and isinstance(kw.value, ast.Constant)), None)
                fn = call.func
                name = fn.id if isinstance(fn, ast.Name) else (
                    fn.attr if isinstance(fn, ast.Attribute) else None)
                if name:
                    raised_types.add(name)
                if ec is not None:
                    produced.add(ec)
                elif name in defaults:
                    produced.add(defaults[name])
    return produced, raised_types, defaults


def test_every_catalog_class_is_raisable():
    """No dead entries: every catalog class is either produced by a
    raise site, or is the family default of an exception type that IS
    raised (sites may narrow the class per condition, like the
    reference's DeltaErrors.scala factories), or the default of a base
    class whose subclasses are raised (e.g. ConcurrentModification)."""
    produced, raised_types, defaults = _produced_classes()
    family_defaults = {defaults[t] for t in raised_types
                       if t in defaults}
    # base classes of raised subclasses
    base_classes = set()
    for _n, obj in inspect.getmembers(E, inspect.isclass):
        if issubclass(obj, DeltaError) and obj.__name__ in raised_types:
            for parent in obj.__mro__[1:]:
                if parent is DeltaError or not issubclass(parent,
                                                          DeltaError):
                    break
                base_classes.add(parent.error_class)
    # classes the AST census cannot attribute to a raise site:
    # UnsupportedTableFeatureError picks its class inside __init__, and
    # MergeBuilder._validate_clauses raises through a data-driven loop
    # (error_class=ec) — covered by test_merge_clause_validation
    special = {
        "DELTA_UNSUPPORTED_FEATURES_FOR_WRITE",
        "DELTA_NON_LAST_MATCHED_CLAUSE_OMIT_CONDITION",
        "DELTA_NON_LAST_NOT_MATCHED_CLAUSE_OMIT_CONDITION",
        "DELTA_NON_LAST_NOT_MATCHED_BY_SOURCE_CLAUSE_OMIT_CONDITION",
    }
    ok = produced | family_defaults | base_classes | special | \
        {"DELTA_ERROR"}
    dead = sorted(set(error_catalog()) - ok)
    assert not dead, f"catalog entries no raise site can produce: {dead}"


def test_every_explicit_error_class_is_cataloged():
    """The inverse: every error_class= string used at a raise site (and
    every class default) exists in the catalog — no typo'd classes."""
    produced, _raised, defaults = _produced_classes()
    catalog = error_catalog()
    unknown = sorted((produced | set(defaults.values())) - set(catalog))
    assert not unknown, f"uncataloged error classes in use: {unknown}"


# ---- behavior tests for the round-5 validations ----------------------

def test_new_validation_conditions(tmp_path):
    """The genuinely-new checks added with their catalog classes."""
    import pyarrow as pa
    import pytest

    import delta_tpu.api as dta
    from delta_tpu.errors import DeltaError, error_info
    from delta_tpu.table import Table

    p = str(tmp_path / "t")
    dta.write_table(p, pa.table({"id": pa.array([1, 2], pa.int64())}))
    t = Table.for_path(p)

    def klass(fn):
        with __import__("pytest").raises(DeltaError) as ei:
            fn()
        return error_info(ei.value)["errorClass"]

    # CDC range start > end
    from delta_tpu.read.cdc import table_changes
    from delta_tpu.sql import sql

    sql(f"ALTER TABLE '{p}' SET TBLPROPERTIES "
        f"('delta.enableChangeDataFeed' = 'true')")
    assert klass(lambda: table_changes(t, 5, 1)) == "DELTA_INVALID_CDC_RANGE"

    # time travel: both version and timestamp
    assert klass(lambda: dta.read_table(p, version=0, timestamp_ms=1)) \
        == "DELTA_ONEOF_IN_TIMETRAVEL"

    # unset non-existent property
    from delta_tpu.commands.alter import unset_properties

    assert klass(lambda: unset_properties(t, ["delta.nope"])) \
        == "DELTA_UNSET_NON_EXISTENT_PROPERTY"

    # invalid characters in column names without column mapping
    assert klass(lambda: dta.write_table(
        str(tmp_path / "bad"), pa.table({"a b": [1]}))) \
        == "DELTA_INVALID_CHARACTERS_IN_COLUMN_NAME"

    # non-boolean CHECK constraint
    from delta_tpu.constraints import add_constraint

    assert klass(lambda: add_constraint(t, "c1", "id")) \
        == "DELTA_NON_BOOLEAN_CHECK_CONSTRAINT"

    # malformed interval table property
    from delta_tpu.config import _parse_interval_ms

    assert klass(lambda: _parse_interval_ms("interval five days")) \
        == "DELTA_INVALID_INTERVAL"
    assert klass(lambda: _parse_interval_ms("interval")) \
        == "DELTA_INVALID_CALENDAR_INTERVAL_EMPTY"

    # reserved CDC column names on write
    assert klass(lambda: dta.write_table(
        p, pa.table({"id": [3], "_change_type": ["x"]}), mode="append")) \
        == "RESERVED_CDC_COLUMNS_ON_WRITE"


def test_error_info_subclassed_iceberg_compat(tmp_path):
    """Dotted subclass keys (the reference's errorClass.subClass shape)
    resolve through error_info."""
    from delta_tpu.errors import error_catalog

    entry = error_catalog()[
        "DELTA_ICEBERG_COMPAT_VIOLATION.DELETION_VECTORS_SHOULD_BE_DISABLED"]
    assert entry["sqlState"]


def test_invalid_column_chars_nested_and_alter(tmp_path):
    """The name-character rule holds at every schema change (the
    update_metadata choke point), including nested struct fields and
    ALTER ADD COLUMNS — not just top-level creation."""
    import pyarrow as pa
    import pytest

    import delta_tpu.api as dta
    from delta_tpu.commands.alter import add_columns
    from delta_tpu.errors import DeltaError, error_info
    from delta_tpu.models.schema import LONG, StructField
    from delta_tpu.table import Table

    # nested struct child with a bad name
    p1 = str(tmp_path / "nested")
    nested = pa.table({"s": pa.array([{"a b": 1}],
                                     pa.struct([("a b", pa.int64())]))})
    with pytest.raises(DeltaError) as ei:
        dta.write_table(p1, nested)
    assert error_info(ei.value)["errorClass"] == \
        "DELTA_INVALID_CHARACTERS_IN_COLUMN_NAME"

    # ALTER ADD COLUMNS with a bad name on an existing table
    p2 = str(tmp_path / "plain")
    dta.write_table(p2, pa.table({"id": pa.array([1], pa.int64())}))
    with pytest.raises(DeltaError) as ei:
        add_columns(Table.for_path(p2), [StructField("a b", LONG)])
    assert error_info(ei.value)["errorClass"] == \
        "DELTA_INVALID_CHARACTERS_IN_COLUMN_NAME"


def test_round5_command_validation_conditions(tmp_path):
    """Batch of reference conditions added in round 5: OPTIMIZE FULL,
    zorder-without-stats, clustering limits, restore timestamps,
    clone/convert targets, multi-format time travel."""
    import time

    import pyarrow as pa
    import pytest

    import delta_tpu.api as dta
    from delta_tpu.errors import DeltaError, error_info
    from delta_tpu.sql import sql
    from delta_tpu.table import Table

    def klass(fn):
        with pytest.raises(DeltaError) as ei:
            fn()
        return error_info(ei.value)["errorClass"]

    p = str(tmp_path / "t")
    dta.write_table(p, pa.table({
        "id": pa.array([1, 2], pa.int64()),
        "v": pa.array([1.0, 2.0]),
        "tags": pa.array([[1], [2]], pa.list_(pa.int64()))}))
    t = Table.for_path(p)

    # OPTIMIZE FULL on a non-clustered table
    assert klass(lambda: sql(f"OPTIMIZE '{p}' FULL")) \
        == "DELTA_OPTIMIZE_FULL_NOT_SUPPORTED"

    # zorder on a column with no collected stats
    sql(f"ALTER TABLE '{p}' SET TBLPROPERTIES "
        f"('delta.dataSkippingStatsColumns' = 'id')")
    assert klass(lambda: t.optimize().execute_zorder_by("v")) \
        == "DELTA_ZORDERING_ON_COLUMN_WITHOUT_STATS"

    # clustering: >4 columns / non-skippable datatype
    from delta_tpu.clustering import set_clustering_columns

    assert klass(lambda: set_clustering_columns(
        t, ["a", "b", "c", "d", "e"])) \
        == "DELTA_CLUSTER_BY_INVALID_NUM_COLUMNS"
    assert klass(lambda: set_clustering_columns(t, ["tags"])) \
        == "DELTA_CLUSTERING_COLUMNS_DATATYPE_NOT_SUPPORTED"

    # clustered OPTIMIZE rejects predicates; FULL works end-to-end
    set_clustering_columns(t, ["id"])
    from delta_tpu.expressions import col, lit

    assert klass(lambda: t.optimize().where(
        col("id") > lit(0)).execute_compaction()) \
        == "DELTA_CLUSTERING_WITH_PARTITION_PREDICATE"
    m = t.optimize().execute_full()
    assert m.num_files_added >= 1

    # restore to out-of-range timestamps
    from delta_tpu.commands.restore import restore

    assert klass(lambda: restore(t, timestamp_ms=1)) \
        == "DELTA_CANNOT_RESTORE_TIMESTAMP_EARLIER"
    assert klass(lambda: restore(
        t, timestamp_ms=int(time.time() * 1000) + 10**9)) \
        == "DELTA_CANNOT_RESTORE_TIMESTAMP_GREATER"

    # clone into a non-empty, non-table directory
    from delta_tpu.commands.restore import clone

    junkdir = tmp_path / "junkdir"
    junkdir.mkdir()
    (junkdir / "x.bin").write_bytes(b"x")
    assert klass(lambda: clone(t, str(junkdir))) \
        == "DELTA_UNSUPPORTED_NON_EMPTY_CLONE"

    # convert: missing / non-parquet provider
    assert klass(lambda: sql(f"CONVERT TO DELTA '{p}'")) \
        == "DELTA_MISSING_PROVIDER_FOR_CONVERT"
    assert klass(lambda: sql(f"CONVERT TO DELTA iceberg.'{p}'")) \
        == "DELTA_CONVERT_NON_PARQUET_TABLE"

    # both time-travel formats on one table reference
    from delta_tpu.sqlengine import execute_select

    assert klass(lambda: execute_select(
        f"SELECT * FROM '{p}' VERSION AS OF 0 TIMESTAMP AS OF 1")) \
        == "DELTA_UNSUPPORTED_TIME_TRAVEL_MULTIPLE_FORMATS"


def test_round5_streaming_cdc_validation_conditions(tmp_path):
    """Streaming option/offset validation + CDC boundary classes."""
    import pyarrow as pa
    import pytest

    import delta_tpu.api as dta
    from delta_tpu.errors import DeltaError, error_info
    from delta_tpu.sql import sql
    from delta_tpu.streaming import DeltaSource, DeltaSourceOffset
    from delta_tpu.table import Table

    def klass(fn):
        with pytest.raises(DeltaError) as ei:
            fn()
        return error_info(ei.value)["errorClass"]

    p = str(tmp_path / "t")
    dta.write_table(p, pa.table({"id": pa.array([1, 2], pa.int64())}))
    dta.write_table(p, pa.table({"id": pa.array([3], pa.int64())}),
                    mode="append")
    t = Table.for_path(p)

    # option parsing
    assert klass(lambda: DeltaSource.from_options(
        t, {"startingVersion": "banana"})) == "DELTA_INVALID_SOURCE_VERSION"
    assert klass(lambda: DeltaSource.from_options(
        t, {"startingVersion": "1", "startingTimestamp": "1"})) \
        == "DELTA_STARTING_VERSION_AND_TIMESTAMP_BOTH_SET"
    assert klass(lambda: DeltaSource.from_options(
        t, {"maxFilesPerTrigger": "0"})) == "DELTA_UNKNOWN_READ_LIMIT"
    assert klass(lambda: DeltaSource.from_options(
        t, {"ignoreDeletes": "maybe"})) == "DELTA_ILLEGAL_OPTION"
    src, limits = DeltaSource.from_options(
        t, {"startingVersion": "latest", "maxFilesPerTrigger": "7"})
    assert limits.max_files == 7
    assert src.latest_offset() is None  # nothing after "latest"

    # startingTimestamp resolves to the first commit at/after it
    ts1 = t.snapshot_at(1)  # noqa: F841 — materialize version 1
    from delta_tpu.history import get_history

    hist = {r.version: r.timestamp_ms for r in get_history(t)}
    src2, _ = DeltaSource.from_options(
        t, {"startingTimestamp": str(hist[1])})
    off = src2.latest_offset()
    batch = src2.get_batch(None, off)
    assert sorted(batch.column("id").to_pylist()) == [3]  # v1 only

    # offset wire-format validation
    assert klass(lambda: DeltaSourceOffset.from_json("not json")) \
        == "DELTA_INVALID_SOURCE_OFFSET_FORMAT"
    assert klass(lambda: DeltaSourceOffset.from_json(
        '{"sourceVersion": 99, "reservoirVersion": 1, "index": -1}')) \
        == "DELTA_INVALID_SOURCE_VERSION"
    rt = DeltaSourceOffset.from_json(
        DeltaSourceOffset(1, -1, reservoir_id="abc").to_json())
    assert rt.reservoir_id == "abc" and rt.reservoir_version == 1

    # offset from a different table id is rejected
    src3 = DeltaSource(t)
    foreign = DeltaSourceOffset(0, -1, reservoir_id="some-other-table")
    assert klass(lambda: src3.latest_offset(foreign)) \
        == "DIFFERENT_DELTA_TABLE_READ_BY_STREAMING_SOURCE"

    # CDC boundary validation
    from delta_tpu.read.cdc import table_changes

    sql(f"ALTER TABLE '{p}' SET TBLPROPERTIES "
        f"('delta.enableChangeDataFeed' = 'true')")  # version 2
    assert klass(lambda: table_changes(t)) == "DELTA_NO_START_FOR_CDC_READ"
    assert klass(lambda: table_changes(
        t, starting_version=0, starting_timestamp=1)) \
        == "DELTA_MULTIPLE_CDC_BOUNDARY"
    assert klass(lambda: table_changes(
        t, starting_version=0, ending_version=1, ending_timestamp=2)) \
        == "DELTA_MULTIPLE_CDC_BOUNDARY"
    # the pre-enablement range never recorded change data
    assert klass(lambda: table_changes(t, starting_version=0)) \
        == "DELTA_MISSING_CHANGE_DATA"
    # post-enablement range works, including timestamp boundaries
    dta.write_table(p, pa.table({"id": pa.array([4], pa.int64())}),
                    mode="append")  # version 3
    changes = table_changes(t, starting_version=3)
    assert changes.column("id").to_pylist() == [4]
    hist = {r.version: r.timestamp_ms for r in get_history(t)}
    by_ts = table_changes(t, starting_timestamp=hist[3])
    assert by_ts.column("id").to_pylist() == [4]


def test_round5_schema_conf_dv_validation_conditions(tmp_path):
    """Batch C: property/coordinated-commits guards, nested ALTER
    errors, partition validation, DV descriptor validation."""
    import dataclasses

    import pyarrow as pa
    import pytest

    import delta_tpu.api as dta
    from delta_tpu.errors import DeltaError, error_info
    from delta_tpu.table import Table

    def klass(fn):
        with pytest.raises(DeltaError) as ei:
            fn()
        return error_info(ei.value)["errorClass"]

    p = str(tmp_path / "t")
    dta.write_table(p, pa.table({
        "id": pa.array([1, 2], pa.int64()),
        "s": pa.array([{"a": 1}, {"a": 2}],
                      pa.struct([("a", pa.int64())]))}))
    t = Table.for_path(p)

    from delta_tpu.commands.alter import (
        add_columns,
        drop_column,
        set_properties,
        unset_properties,
    )
    from delta_tpu.models.schema import LONG, StructField

    # unknown delta.* property / bad value / bad autoCompact value
    assert klass(lambda: set_properties(
        t, {"delta.checkpointIntervall": "10"})) \
        == "DELTA_UNKNOWN_CONFIGURATION"
    assert klass(lambda: set_properties(
        t, {"delta.checkpointInterval": "many"})) \
        == "DELTA_VIOLATE_TABLE_PROPERTY_VALIDATION_FAILED"
    assert klass(lambda: set_properties(
        t, {"delta.autoOptimize.autoCompact": "sometimes"})) \
        == "DELTA_INVALID_AUTO_COMPACT_TYPE"

    # coordinated-commits guards (non-CC table first)
    from delta_tpu.coordinatedcommits.client import (
        COORDINATOR_CONF_KEY,
        COORDINATOR_NAME_KEY,
        TABLE_CONF_KEY,
    )

    assert klass(lambda: set_properties(
        t, {COORDINATOR_NAME_KEY: "x"})) \
        == "DELTA_MUST_SET_ALL_COORDINATED_COMMITS_CONFS_IN_COMMAND"
    assert klass(lambda: set_properties(
        t, {COORDINATOR_NAME_KEY: "x", COORDINATOR_CONF_KEY: "{}",
            TABLE_CONF_KEY: "{}"})) \
        == "DELTA_CONF_OVERRIDE_NOT_SUPPORTED_IN_COMMAND"
    assert klass(lambda: set_properties(
        t, {COORDINATOR_NAME_KEY: "x", COORDINATOR_CONF_KEY: "{}",
            "delta.enableInCommitTimestamps": "true"})) \
        == "DELTA_CANNOT_SET_COORDINATED_COMMITS_DEPENDENCIES"
    # now a CC table (simulated existing confs)
    from delta_tpu.coordinatedcommits.client import (
        validate_cc_alter_set,
        validate_cc_alter_unset,
    )

    existing = {COORDINATOR_NAME_KEY: "c", COORDINATOR_CONF_KEY: "{}"}
    assert klass(lambda: validate_cc_alter_set(
        existing, {COORDINATOR_NAME_KEY: "other",
                   COORDINATOR_CONF_KEY: "{}"})) \
        == "DELTA_CANNOT_OVERRIDE_COORDINATED_COMMITS_CONFS"
    assert klass(lambda: validate_cc_alter_set(
        existing, {"delta.enableInCommitTimestamps": "false"})) \
        == "DELTA_CANNOT_MODIFY_COORDINATED_COMMITS_DEPENDENCIES"
    assert klass(lambda: validate_cc_alter_unset(
        existing, [COORDINATOR_NAME_KEY])) \
        == "DELTA_CANNOT_UNSET_COORDINATED_COMMITS_CONFS"
    assert klass(lambda: validate_cc_alter_unset(
        existing, ["delta.enableInCommitTimestamps"])) \
        == "DELTA_CANNOT_MODIFY_COORDINATED_COMMITS_DEPENDENCIES"
    # plain property set/unset still works
    set_properties(t, {"delta.checkpointInterval": "20",
                       "myapp.custom": "anything"})
    unset_properties(t, ["myapp.custom"])

    # nested ALTER errors + the working nested paths
    assert klass(lambda: add_columns(
        t, [StructField("nope.b", LONG)])) \
        == "DELTA_ADD_COLUMN_STRUCT_NOT_FOUND"
    assert klass(lambda: add_columns(
        t, [StructField("id.b", LONG)])) \
        == "DELTA_ADD_COLUMN_PARENT_NOT_STRUCT"
    add_columns(t, [StructField("s.b", LONG)])
    snap = t.latest_snapshot()
    s_field = next(f for f in snap.schema.fields if f.name == "s")
    assert [f.name for f in s_field.dataType.fields] == ["a", "b"]
    assert klass(lambda: drop_column(t, "id.x")) \
        == "DELTA_UNSUPPORTED_DROP_COLUMN"  # mapping off first
    set_properties(t, {"delta.columnMapping.mode": "name"})
    assert klass(lambda: drop_column(t, "id.x")) \
        == "DELTA_UNSUPPORTED_DROP_NESTED_COLUMN_FROM_NON_STRUCT_TYPE"
    drop_column(t, "s.b")
    snap = t.latest_snapshot()
    s_field = next(f for f in snap.schema.fields if f.name == "s")
    assert [f.name for f in s_field.dataType.fields] == ["a"]

    # partition validation at metadata update
    assert klass(lambda: dta.write_table(
        str(tmp_path / "allpart"),
        pa.table({"a": [1], "b": [2]}), partition_by=["a", "b"])) \
        == "DELTA_CANNOT_USE_ALL_COLUMNS_FOR_PARTITION"
    assert klass(lambda: dta.write_table(
        str(tmp_path / "badpart"),
        pa.table({"a": [1], "s": pa.array(
            [{"x": 1}], pa.struct([("x", pa.int64())]))}),
        partition_by=["s"])) == "DELTA_INVALID_PARTITION_COLUMN_TYPE"

    # DV descriptor out of sync with its bitmap
    from delta_tpu.dv.descriptor import load_deletion_vector
    from delta_tpu.dv.roaring import RoaringBitmapArray
    import base64

    import numpy as np

    bm = RoaringBitmapArray(np.array([1, 5, 9], np.uint64))
    blob = bm.serialize_delta()
    inline = base64.b85encode(blob).decode()
    good = {"storageType": "i", "pathOrInlineDv": inline,
            "sizeInBytes": len(blob), "cardinality": 3}
    assert list(load_deletion_vector(t.engine, p, good)) == [1, 5, 9]
    assert klass(lambda: load_deletion_vector(
        t.engine, p, {**good, "sizeInBytes": len(blob) + 1})) \
        == "DELTA_DELETION_VECTOR_SIZE_MISMATCH"
    assert klass(lambda: load_deletion_vector(
        t.engine, p, {**good, "cardinality": 7})) \
        == "DELTA_DELETION_VECTOR_CARDINALITY_MISMATCH"


def test_round5_review_fix_regressions(tmp_path):
    """Regressions for the round-5 review findings."""
    import time as _time

    import pyarrow as pa
    import pytest

    import delta_tpu.api as dta
    from delta_tpu.errors import DeltaError, error_info
    from delta_tpu.sql import sql
    from delta_tpu.table import Table

    def klass(fn):
        with pytest.raises(DeltaError) as ei:
            fn()
        return error_info(ei.value)["errorClass"]

    p = str(tmp_path / "t")
    dta.write_table(p, pa.table({"id": pa.array([1], pa.int64())}),
                    properties={"delta.enableChangeDataFeed": "true"})
    _time.sleep(0.05)
    dta.write_table(p, pa.table({"id": pa.array([2], pa.int64())}),
                    mode="append")
    t = Table.for_path(p)

    # CDC startingTimestamp is at-or-AFTER: a midpoint timestamp must
    # exclude the earlier commit
    from delta_tpu.history import get_history
    from delta_tpu.read.cdc import table_changes

    hist = {r.version: r.timestamp_ms for r in get_history(t)}
    assert hist[1] > hist[0], "need distinct mtimes for the boundary"
    mid = hist[0] + 1
    ch = table_changes(t, starting_timestamp=mid)
    assert ch.column("id").to_pylist() == [2]

    # a trailing token named 'version' after a time-travel clause must
    # produce a clean parse error, not an IndexError (the multi-format
    # lookahead reads one token past the clause)
    from delta_tpu.errors import SqlParseError

    with pytest.raises(SqlParseError):
        sql(f"SELECT id FROM '{p}' VERSION AS OF 0 version")

    # inventory vacuum must NOT advance the LITE watermark
    import json as _json
    import os as _os

    inv = pa.table({"path": ["x"], "length": [1], "isDir": [False],
                    "modificationTime": [0]})
    t.vacuum(retention_hours=0, inventory=inv)
    info = _os.path.join(p, "_delta_log", "_last_vacuum_info")
    assert not _os.path.exists(info)

    # corrupted sourceVersion type -> offset-format error, not ValueError
    from delta_tpu.streaming import DeltaSourceOffset

    assert klass(lambda: DeltaSourceOffset.from_json(
        '{"reservoirVersion": 1, "index": -1, "sourceVersion": "abc"}')) \
        == "DELTA_INVALID_SOURCE_OFFSET_FORMAT"

    # OPTIMIZE FULL + ZORDER BY is contradictory, not silently dropped
    assert klass(lambda: sql(
        f"OPTIMIZE '{p}' FULL ZORDER BY (id)")) \
        == "DELTA_CLUSTERING_WITH_ZORDER_BY"

    # every boolean property validates strictly at SET time
    from delta_tpu.commands.alter import set_properties

    assert klass(lambda: set_properties(
        t, {"delta.appendOnly": "yess"})) \
        == "DELTA_VIOLATE_TABLE_PROPERTY_VALIDATION_FAILED"


def test_round5_colgen_write_log_validation_conditions(tmp_path):
    """Batch D: identity/generated declaration + dependency guards,
    empty data, INSERT mismatch, log-integrity classes."""
    import os as _os

    import pyarrow as pa
    import pytest

    import delta_tpu.api as dta
    from delta_tpu.colgen import generated_field, identity_field
    from delta_tpu.errors import DeltaError, error_info
    from delta_tpu.models.schema import (
        LONG,
        STRING,
        StructField,
        StructType,
        schema_to_json,
    )
    from delta_tpu.sql import sql
    from delta_tpu.table import Table

    def klass(fn):
        with pytest.raises(DeltaError) as ei:
            fn()
        return error_info(ei.value)["errorClass"]

    def create(schema_fields, path, partition_by=None):
        t = Table.for_path(str(tmp_path / path))
        b = t.create_transaction_builder("CREATE TABLE") \
            .with_schema(schema_to_json(StructType(schema_fields)))
        if partition_by:
            b = b.with_partition_columns(partition_by)
        return b.build()

    # identity declaration invariants
    ident = identity_field("id")
    both = StructField("id", LONG, metadata={
        "delta.identity.start": 1, "delta.identity.step": 1,
        "delta.generationExpression": "x"})
    assert klass(lambda: create([both, StructField("x", LONG)], "t1")) \
        == "DELTA_IDENTITY_COLUMNS_WITH_GENERATED_EXPRESSION"
    assert klass(lambda: create([ident, StructField("x", LONG)], "t2",
                                partition_by=["id"])) \
        == "DELTA_IDENTITY_COLUMNS_PARTITION_NOT_SUPPORTED"
    bad_type = StructField("id", STRING, metadata={
        "delta.identity.start": 1, "delta.identity.step": 1})
    assert klass(lambda: create([bad_type, StructField("x", LONG)],
                                "t3")) \
        == "DELTA_IDENTITY_COLUMNS_UNSUPPORTED_DATA_TYPE"
    gen_bad = generated_field("g", LONG, "missing_col")
    assert klass(lambda: create([StructField("x", LONG), gen_bad],
                                "t4")) \
        == "DELTA_INVALID_GENERATED_COLUMN_REFERENCES"
    assert klass(lambda: create([], "t5")) == "DELTA_EMPTY_DATA"

    # UPDATE of an identity column
    p = str(tmp_path / "ident")
    t = Table.for_path(p)
    t.create_transaction_builder("CREATE TABLE").with_schema(
        schema_to_json(StructType([ident, StructField("x", LONG)]))
    ).build().commit()
    dta.write_table(p, pa.table({"x": pa.array([1, 2], pa.int64())}),
                    mode="append")
    from delta_tpu.commands.dml import update
    from delta_tpu.expressions import col, lit

    assert klass(lambda: update(t, {"id": lit(99)}, col("x") > lit(0))) \
        == "DELTA_IDENTITY_COLUMNS_UPDATE_NOT_SUPPORTED"

    # dependent-column guards (generated + constraint)
    p2 = str(tmp_path / "dep")
    t2 = Table.for_path(p2)
    t2.create_transaction_builder("CREATE TABLE").with_schema(
        schema_to_json(StructType([
            StructField("base", LONG),
            StructField("other", LONG),
            generated_field("twice", LONG, "base")]))
    ).build().commit()
    from delta_tpu.commands.alter import rename_column, set_properties

    set_properties(t2, {"delta.columnMapping.mode": "name"})
    from delta_tpu.commands.alter import drop_column
    from delta_tpu.constraints import add_constraint

    assert klass(lambda: drop_column(t2, "base")) \
        == "DELTA_GENERATED_COLUMNS_DEPENDENT_COLUMN_CHANGE"
    assert klass(lambda: rename_column(t2, "base", "b2")) \
        == "DELTA_GENERATED_COLUMNS_DEPENDENT_COLUMN_CHANGE"
    add_constraint(t2, "pos", "other > 0")
    assert klass(lambda: drop_column(t2, "other")) \
        == "DELTA_CONSTRAINT_DEPENDENT_COLUMN_CHANGE"

    # MERGE INSERT column/value count mismatch shares the arity class
    p3 = str(tmp_path / "ins")
    dta.write_table(p3, pa.table({"a": pa.array([1], pa.int64())}))
    assert klass(lambda: sql(
        f"MERGE INTO '{p3}' AS t USING '{p3}' AS s ON t.a = s.a "
        "WHEN NOT MATCHED THEN INSERT (a) VALUES (s.a, 1)")) \
        == "DELTA_INSERT_COLUMN_ARITY_MISMATCH"

    # mid-range log hole past the checkpoint -> not contiguous
    p4 = str(tmp_path / "gap")
    for i in range(4):
        dta.write_table(p4, pa.table({"a": pa.array([i], pa.int64())}),
                        mode="error" if i == 0 else "append")
    t4 = Table.for_path(p4)
    from delta_tpu.streaming import DeltaSource

    _os.unlink(_os.path.join(p4, "_delta_log", f"{2:020d}.json"))
    # a FRESH listing detects the hole at segment build
    assert klass(lambda: Table.for_path(p4).latest_snapshot()) \
        == "DELTA_TRUNCATED_TRANSACTION_LOG"
    # the streaming guard sees the hole only through a CACHED listing
    # (the segment still brackets the vanished commit); it must
    # classify it as non-contiguous, not as expiry
    from delta_tpu.streaming.source import _ExpiryGuard

    class _StubSeg:
        version = 3
        checkpoint_version = None
        deltas = [type("F", (), {"path": _os.path.join(
            p4, "_delta_log", f"{v:020d}.json")})() for v in (1, 2, 3)]

    class _StubSnap:
        log_segment = _StubSeg()

    class _StubTable:
        engine = t4.engine
        log_path = t4.log_path

        def latest_snapshot(self):
            return _StubSnap()

    guard = _ExpiryGuard(_StubTable(), "stream")
    assert klass(lambda: guard.check(2)) \
        == "DELTA_VERSIONS_NOT_CONTIGUOUS"


def test_round5_dependency_guard_review_fixes(tmp_path):
    """Nested-path dependency guards + generated-referencing-generated
    rejection (review findings)."""
    import pyarrow as pa
    import pytest

    from delta_tpu.errors import DeltaError, error_info
    from delta_tpu.models.schema import (
        LONG,
        StructField,
        StructType,
        schema_to_json,
    )
    from delta_tpu.table import Table
    from delta_tpu.colgen import generated_field

    def klass(fn):
        with pytest.raises(DeltaError) as ei:
            fn()
        return error_info(ei.value)["errorClass"]

    # generated column referencing another generated column
    t0 = Table.for_path(str(tmp_path / "gg"))
    b = t0.create_transaction_builder("CREATE TABLE").with_schema(
        schema_to_json(StructType([
            StructField("x", LONG),
            generated_field("g1", LONG, "x"),
            generated_field("g2", LONG, "g1")])))
    assert klass(lambda: b.build().commit()) \
        == "DELTA_INVALID_GENERATED_COLUMN_REFERENCES"

    # generated column referencing a NESTED field blocks dropping it
    p = str(tmp_path / "nested")
    t = Table.for_path(p)
    inner = StructType([StructField("x", LONG), StructField("y", LONG)])
    t.create_transaction_builder("CREATE TABLE").with_schema(
        schema_to_json(StructType([
            StructField("s", inner),
            generated_field("g", LONG, "s.x")]))).build().commit()
    from delta_tpu.commands.alter import drop_column, set_properties

    set_properties(t, {"delta.columnMapping.mode": "name"})
    assert klass(lambda: drop_column(t, "s.x")) \
        == "DELTA_GENERATED_COLUMNS_DEPENDENT_COLUMN_CHANGE"
    assert klass(lambda: drop_column(t, "s")) \
        == "DELTA_GENERATED_COLUMNS_DEPENDENT_COLUMN_CHANGE"
    drop_column(t, "s.y")  # un-referenced sibling drops fine


def test_round5_dynamic_overwrite_and_schema_log(tmp_path):
    """Batch E: dynamic partition overwrite (feature + guards),
    dataChange=false discipline, schema-log integrity classes."""
    import pyarrow as pa
    import pytest

    import delta_tpu.api as dta
    from delta_tpu.errors import DeltaError, error_info
    from delta_tpu.expressions import col, lit
    from delta_tpu.table import Table

    def klass(fn):
        with pytest.raises(DeltaError) as ei:
            fn()
        return error_info(ei.value)["errorClass"]

    p = str(tmp_path / "t")
    dta.write_table(p, pa.table({
        "id": pa.array([1, 2, 3, 4], pa.int64()),
        "part": pa.array(["a", "a", "b", "b"])}),
        partition_by=["part"])

    # dynamic overwrite replaces ONLY the partitions in the new data
    dta.write_table(p, pa.table({
        "id": pa.array([10], pa.int64()),
        "part": pa.array(["a"])}),
        mode="overwrite", partition_overwrite_mode="dynamic")
    out = dta.read_table(p).sort_by("id")
    assert out.column("id").to_pylist() == [3, 4, 10]
    assert sorted(set(out.column("part").to_pylist())) == ["a", "b"]

    # option conflicts
    assert klass(lambda: dta.write_table(
        p, pa.table({"id": pa.array([1], pa.int64()),
                     "part": pa.array(["a"])}),
        mode="overwrite", partition_overwrite_mode="dynamic",
        replace_where=col("part") == lit("a"))) \
        == "DELTA_REPLACE_WHERE_WITH_DYNAMIC_PARTITION_OVERWRITE"
    assert klass(lambda: dta.write_table(
        p, pa.table({"id": pa.array([1], pa.int64()),
                     "part": pa.array(["a"])}),
        mode="overwrite", partition_overwrite_mode="dynamic",
        overwrite_schema=True)) \
        == "DELTA_OVERWRITE_SCHEMA_WITH_DYNAMIC_PARTITION_OVERWRITE"
    assert klass(lambda: dta.write_table(
        p, pa.table({"id": pa.array([1], pa.int64()),
                     "part": pa.array(["a"])}),
        mode="overwrite", data_change=False,
        replace_where=col("part") == lit("a"))) \
        == "DELTA_REPLACE_WHERE_WITH_FILTER_DATA_CHANGE_UNSET"
    assert klass(lambda: dta.write_table(
        str(tmp_path / "new"), pa.table({"id": pa.array([1], pa.int64())}),
        data_change=False)) == "DELTA_DATA_CHANGE_FALSE"
    assert klass(lambda: dta.write_table(
        p, pa.table({"id": pa.array([1], pa.int64()),
                     "part": pa.array(["a"])}),
        mode="overwrite", partition_overwrite_mode="sideways")) \
        == "DELTA_ILLEGAL_OPTION"

    # dataChange=false writes rearrangement adds streams must skip
    v = dta.write_table(p, pa.table({
        "id": pa.array([99], pa.int64()),
        "part": pa.array(["c"])}), mode="append", data_change=False)
    from delta_tpu.models.actions import (
        AddFile,
        actions_from_commit_bytes,
    )
    from delta_tpu.utils import filenames

    t = Table.for_path(p)
    acts = actions_from_commit_bytes(t.engine.fs.read_file(
        filenames.delta_file(t.log_path, v)))
    adds = [a for a in acts if isinstance(a, AddFile)]
    assert adds and all(not a.dataChange for a in adds)

    # schema-log integrity
    from delta_tpu.streaming.schema_log import (
        PersistedMetadata,
        SchemaTrackingLog,
    )

    loc = str(tmp_path / "ckpt")
    log = SchemaTrackingLog(t.engine, loc, "table-A")
    log.append(PersistedMetadata(0, "{}", ["part"], {}))
    # partition schema change is rejected
    assert klass(lambda: log.append(
        PersistedMetadata(1, "{}", ["other"], {}))) \
        == "DELTA_STREAMING_SCHEMA_LOG_INCOMPATIBLE_PARTITION_SCHEMA"
    # wrong table id in a persisted entry
    log2 = SchemaTrackingLog(t.engine, loc, "table-A")
    import os as _os

    evil = _os.path.join(loc, "_schema_log_table-A",
                         f"{1:020d}.json")
    with open(evil, "w") as f:
        f.write(PersistedMetadata(1, "{}", ["part"], {},
                                  table_id="table-B").to_json())
    assert klass(lambda: log2.entries()) \
        == "DELTA_STREAMING_SCHEMA_LOG_INCOMPATIBLE_DELTA_TABLE_ID"
    # corrupt entry
    with open(evil, "w") as f:
        f.write("{not json")
    assert klass(lambda: log2.entries()) \
        == "DELTA_STREAMING_SCHEMA_LOG_DESERIALIZE_FAILED"


def test_round5_batch_e_review_fixes(tmp_path):
    """Review regressions: consistent dataChange on overwrite removes,
    MERGE identity guard, unparseable generation expressions."""
    import pyarrow as pa
    import pytest

    import delta_tpu.api as dta
    from delta_tpu.errors import DeltaError, error_info
    from delta_tpu.models.schema import (
        LONG,
        StructField,
        StructType,
        schema_to_json,
    )
    from delta_tpu.table import Table

    def klass(fn):
        with pytest.raises(DeltaError) as ei:
            fn()
        return error_info(ei.value)["errorClass"]

    # rearrangement overwrite: BOTH adds and removes carry
    # dataChange=false
    p = str(tmp_path / "re")
    dta.write_table(p, pa.table({"id": pa.array([1, 2], pa.int64())}))
    v = dta.write_table(p, pa.table({"id": pa.array([1, 2], pa.int64())}),
                        mode="overwrite", data_change=False)
    from delta_tpu.models.actions import (
        AddFile,
        RemoveFile,
        actions_from_commit_bytes,
    )
    from delta_tpu.utils import filenames

    t = Table.for_path(p)
    acts = actions_from_commit_bytes(
        t.engine.fs.read_file(filenames.delta_file(t.log_path, v)))
    assert all(not a.dataChange for a in acts
               if isinstance(a, (AddFile, RemoveFile)))

    # MERGE update of an identity column is rejected at analysis
    from delta_tpu.colgen import identity_field
    from delta_tpu.expressions import col, lit

    p2 = str(tmp_path / "ident")
    t2 = Table.for_path(p2)
    t2.create_transaction_builder("CREATE TABLE").with_schema(
        schema_to_json(StructType([identity_field("id"),
                                   StructField("x", LONG)]))
    ).build().commit()
    dta.write_table(p2, pa.table({"x": pa.array([1], pa.int64())}),
                    mode="append")
    from delta_tpu.commands.merge import merge

    src = pa.table({"x": pa.array([1], pa.int64())})
    assert klass(lambda: merge(t2, src, on=col("target.x") == col("source.x"))
                 .when_matched_update(set={"id": lit(0)}).execute()) \
        == "DELTA_IDENTITY_COLUMNS_UPDATE_NOT_SUPPORTED"

    # unparseable generation expression fails at declaration
    bad = StructField("g", LONG, metadata={
        "delta.generationExpression": "1 +"})
    t3 = Table.for_path(str(tmp_path / "badgen"))
    b = t3.create_transaction_builder("CREATE TABLE").with_schema(
        schema_to_json(StructType([StructField("x", LONG), bad])))
    assert klass(lambda: b.build().commit()) \
        == "DELTA_UNSUPPORTED_EXPRESSION_GENERATED_COLUMN"

"""Error-class catalog: every concrete error type resolves to a stable
class with an SQLSTATE (the reference's delta-error-classes.json role)."""

import inspect

import delta_tpu.errors as E
from delta_tpu.errors import DeltaError, error_catalog, error_info


def _concrete_error_classes():
    out = []
    for _, obj in inspect.getmembers(E, inspect.isclass):
        if issubclass(obj, DeltaError):
            out.append(obj)
    # classes defined elsewhere that carry their own error_class
    from delta_tpu.commands.merge import MergeCardinalityError
    from delta_tpu.log.segment import CorruptLogError

    out += [MergeCardinalityError, CorruptLogError]
    return out


def test_every_error_class_is_in_the_catalog():
    catalog = error_catalog()
    for cls in _concrete_error_classes():
        assert cls.error_class in catalog, cls.__name__
        entry = catalog[cls.error_class]
        assert entry["sqlState"]
        assert entry["message"]


def test_error_classes_are_unique_where_distinct():
    seen = {}
    for cls in _concrete_error_classes():
        if cls.error_class in seen and seen[cls.error_class] is not cls:
            # subclass sharing a parent's class is allowed only for
            # aliases; distinct top-level types must not collide
            assert issubclass(cls, seen[cls.error_class]) or issubclass(
                seen[cls.error_class], cls), (
                f"{cls.__name__} and {seen[cls.error_class].__name__} share "
                f"{cls.error_class}")
        seen.setdefault(cls.error_class, cls)


def test_error_info_structure():
    try:
        raise E.VersionNotFoundError(version=7, earliest=0, latest=3)
    except DeltaError as e:
        info = error_info(e)
    assert info["errorClass"] == "DELTA_VERSION_NOT_FOUND"
    assert info["sqlState"] == "42815"
    assert info["parameters"]["version"] == 7
    assert "version" in info["messageTemplate"]

"""Error-class catalog: every concrete error type resolves to a stable
class with an SQLSTATE (the reference's delta-error-classes.json role)."""

import inspect

import delta_tpu.errors as E
from delta_tpu.errors import DeltaError, error_catalog, error_info


def _concrete_error_classes():
    out = []
    for _, obj in inspect.getmembers(E, inspect.isclass):
        if issubclass(obj, DeltaError):
            out.append(obj)
    # classes defined elsewhere that carry their own error_class
    from delta_tpu.commands.merge import MergeCardinalityError
    from delta_tpu.log.segment import CorruptLogError

    out += [MergeCardinalityError, CorruptLogError]
    return out


def test_every_error_class_is_in_the_catalog():
    catalog = error_catalog()
    for cls in _concrete_error_classes():
        assert cls.error_class in catalog, cls.__name__
        entry = catalog[cls.error_class]
        assert entry["sqlState"]
        assert entry["message"]


def test_error_classes_are_unique_where_distinct():
    seen = {}
    for cls in _concrete_error_classes():
        if cls.error_class in seen and seen[cls.error_class] is not cls:
            # subclass sharing a parent's class is allowed only for
            # aliases; distinct top-level types must not collide
            assert issubclass(cls, seen[cls.error_class]) or issubclass(
                seen[cls.error_class], cls), (
                f"{cls.__name__} and {seen[cls.error_class].__name__} share "
                f"{cls.error_class}")
        seen.setdefault(cls.error_class, cls)


def test_error_info_structure():
    try:
        raise E.VersionNotFoundError(version=7, earliest=0, latest=3)
    except DeltaError as e:
        info = error_info(e)
    assert info["errorClass"] == "DELTA_VERSION_NOT_FOUND"
    assert info["sqlState"] == "42815"
    assert info["parameters"]["version"] == 7
    assert "version" in info["messageTemplate"]


# ---- package walk: every raise site is typed + cataloged (r4) --------

import ast
import os

PKG = os.path.dirname(E.__file__)

# exceptions that are NOT user-facing Delta errors: builtins for
# internal invariants, storage-protocol exceptions with documented
# contracts, and parse-layer locals
_ALLOWED_NON_DELTA = {
    "ValueError", "TypeError", "KeyError", "RuntimeError", "IOError",
    "OSError", "FileNotFoundError", "FileExistsError",
    "NotImplementedError", "StopIteration", "TimeoutError",
    "AssertionError", "ConnectionError", "InterruptedError",
    "FileAlreadyExistsError", "PreconditionFailedError",
    "TableAlreadyExistsError", "TableNotInCatalogError",
    "ParseError", "CommitFailedException",
}


def _raise_sites():
    for root, _dirs, files in os.walk(PKG):
        for f in files:
            if not f.endswith(".py"):
                continue
            path = os.path.join(root, f)
            tree = ast.parse(open(path).read(), filename=path)
            for node in ast.walk(tree):
                if not isinstance(node, ast.Raise) or node.exc is None:
                    continue
                exc = node.exc
                if isinstance(exc, ast.Call):
                    exc = exc.func
                if isinstance(exc, ast.Name):
                    yield path, node.lineno, exc.id
                elif isinstance(exc, ast.Attribute):
                    yield path, node.lineno, exc.attr


def test_no_generic_delta_error_raises():
    """All 204 former `raise DeltaError(...)` sites were mapped to
    typed classes in round 4; this pins the count at zero."""
    generic = [f"{os.path.relpath(p, PKG)}:{ln}"
               for p, ln, name in _raise_sites() if name == "DeltaError"]
    assert not generic, (
        f"raise a typed, cataloged subclass instead: {generic}")


def test_every_raise_site_is_typed_or_allowed():
    known = {n for n, obj in inspect.getmembers(E, inspect.isclass)
             if issubclass(obj, DeltaError)}
    # typed DeltaError subclasses defined next to their subsystem
    known |= {"MergeCardinalityError", "CorruptLogError",
              "RemoteDeltaError", "PostCommitHookError",
              "SchemaEvolutionRequiresRestart"}
    extra_builtin = {"AttributeError", "EOFError", "SystemExit"}
    bad = []
    for p, ln, name in _raise_sites():
        if name in known or name in _ALLOWED_NON_DELTA \
                or name in extra_builtin:
            continue
        if name.startswith("_"):
            continue  # module-internal control-flow exceptions
        if name[0].islower() or name in ("e", "err", "exc"):
            continue  # re-raise of a caught local
        bad.append(f"{os.path.relpath(p, PKG)}:{ln}: {name}")
    assert not bad, f"unclassified raise sites: {bad}"


def test_catalog_round4_floor():
    # reference catalog is ~300 classes and growing; pin our floor
    assert len(error_catalog()) >= 70

"""Error-class catalog: every concrete error type resolves to a stable
class with an SQLSTATE (the reference's delta-error-classes.json role)."""

import inspect

import delta_tpu.errors as E
from delta_tpu.errors import DeltaError, error_catalog, error_info


def _concrete_error_classes():
    out = []
    for _, obj in inspect.getmembers(E, inspect.isclass):
        if issubclass(obj, DeltaError):
            out.append(obj)
    # classes defined elsewhere that carry their own error_class
    from delta_tpu.commands.merge import MergeCardinalityError
    from delta_tpu.log.segment import CorruptLogError

    out += [MergeCardinalityError, CorruptLogError]
    return out


def test_every_error_class_is_in_the_catalog():
    catalog = error_catalog()
    for cls in _concrete_error_classes():
        assert cls.error_class in catalog, cls.__name__
        entry = catalog[cls.error_class]
        assert entry["sqlState"]
        assert entry["message"]


def test_error_classes_are_unique_where_distinct():
    seen = {}
    for cls in _concrete_error_classes():
        if cls.error_class in seen and seen[cls.error_class] is not cls:
            # subclass sharing a parent's class is allowed only for
            # aliases; distinct top-level types must not collide
            assert issubclass(cls, seen[cls.error_class]) or issubclass(
                seen[cls.error_class], cls), (
                f"{cls.__name__} and {seen[cls.error_class].__name__} share "
                f"{cls.error_class}")
        seen.setdefault(cls.error_class, cls)


def test_error_info_structure():
    try:
        raise E.VersionNotFoundError(version=7, earliest=0, latest=3)
    except DeltaError as e:
        info = error_info(e)
    assert info["errorClass"] == "DELTA_VERSION_NOT_FOUND"
    assert info["sqlState"] == "42815"
    assert info["parameters"]["version"] == 7
    assert "version" in info["messageTemplate"]


# ---- package walk: every raise site is typed + cataloged (r4) --------

import ast
import os

PKG = os.path.dirname(E.__file__)

# exceptions that are NOT user-facing Delta errors: builtins for
# internal invariants, storage-protocol exceptions with documented
# contracts, and parse-layer locals
_ALLOWED_NON_DELTA = {
    "ValueError", "TypeError", "KeyError", "RuntimeError", "IOError",
    "OSError", "FileNotFoundError", "FileExistsError",
    "NotImplementedError", "StopIteration", "TimeoutError",
    "AssertionError", "ConnectionError", "InterruptedError",
    "FileAlreadyExistsError", "PreconditionFailedError",
    "TableAlreadyExistsError", "TableNotInCatalogError",
    "ParseError", "CommitFailedException",
    # internal fall-back signal of the page decoder: always caught,
    # the Arrow reader takes over (log/page_decode.py)
    "DecodeUnsupported",
    # storage-protocol error carrying the DynamoDB __type; the arbiter
    # maps the arbitration-relevant case (ConditionalCheckFailed) to
    # FileAlreadyExistsError like the other store clients
    "DynamoDbError",
}


def _raise_sites():
    for root, _dirs, files in os.walk(PKG):
        for f in files:
            if not f.endswith(".py"):
                continue
            path = os.path.join(root, f)
            tree = ast.parse(open(path).read(), filename=path)
            for node in ast.walk(tree):
                if not isinstance(node, ast.Raise) or node.exc is None:
                    continue
                exc = node.exc
                if isinstance(exc, ast.Call):
                    exc = exc.func
                if isinstance(exc, ast.Name):
                    yield path, node.lineno, exc.id
                elif isinstance(exc, ast.Attribute):
                    yield path, node.lineno, exc.attr


def test_no_generic_delta_error_raises():
    """All 204 former `raise DeltaError(...)` sites were mapped to
    typed classes in round 4; this pins the count at zero."""
    generic = [f"{os.path.relpath(p, PKG)}:{ln}"
               for p, ln, name in _raise_sites() if name == "DeltaError"]
    assert not generic, (
        f"raise a typed, cataloged subclass instead: {generic}")


def test_every_raise_site_is_typed_or_allowed():
    known = {n for n, obj in inspect.getmembers(E, inspect.isclass)
             if issubclass(obj, DeltaError)}
    # typed DeltaError subclasses defined next to their subsystem
    known |= {"MergeCardinalityError", "CorruptLogError",
              "RemoteDeltaError", "PostCommitHookError",
              "SchemaEvolutionRequiresRestart"}
    extra_builtin = {"AttributeError", "EOFError", "SystemExit"}
    bad = []
    for p, ln, name in _raise_sites():
        if name in known or name in _ALLOWED_NON_DELTA \
                or name in extra_builtin:
            continue
        if name.startswith("_"):
            continue  # module-internal control-flow exceptions
        if name[0].islower() or name in ("e", "err", "exc"):
            continue  # re-raise of a caught local
        bad.append(f"{os.path.relpath(p, PKG)}:{ln}: {name}")
    assert not bad, f"unclassified raise sites: {bad}"


def test_catalog_round5_floor():
    # reference catalog is ~448 classes; round 5 target was >=200
    assert len(error_catalog()) >= 200


# ---- raisability census: no dead catalog entries (r5) ----------------

def _class_defaults():
    """class name -> default error_class, from every ClassDef in the
    package (AST, so subsystem-local classes count too)."""
    out = {}
    for root, _dirs, files in os.walk(PKG):
        for f in files:
            if not f.endswith(".py"):
                continue
            tree = ast.parse(open(os.path.join(root, f)).read())
            for node in ast.walk(tree):
                if not isinstance(node, ast.ClassDef):
                    continue
                for st in node.body:
                    if isinstance(st, ast.Assign):
                        for tg in st.targets:
                            if isinstance(tg, ast.Name) \
                                    and tg.id == "error_class" \
                                    and isinstance(st.value, ast.Constant):
                                out[node.name] = st.value.value
    return out


def _produced_classes():
    """Error classes some raise site actually produces: an explicit
    error_class= kwarg, or the raised type's default."""
    defaults = _class_defaults()
    produced = set()
    raised_types = set()
    for root, _dirs, files in os.walk(PKG):
        for f in files:
            if not f.endswith(".py"):
                continue
            tree = ast.parse(open(os.path.join(root, f)).read())
            for node in ast.walk(tree):
                if not (isinstance(node, ast.Raise)
                        and isinstance(node.exc, ast.Call)):
                    continue
                call = node.exc
                ec = next((kw.value.value for kw in call.keywords
                           if kw.arg == "error_class"
                           and isinstance(kw.value, ast.Constant)), None)
                fn = call.func
                name = fn.id if isinstance(fn, ast.Name) else (
                    fn.attr if isinstance(fn, ast.Attribute) else None)
                if name:
                    raised_types.add(name)
                if ec is not None:
                    produced.add(ec)
                elif name in defaults:
                    produced.add(defaults[name])
    return produced, raised_types, defaults


def test_every_catalog_class_is_raisable():
    """No dead entries: every catalog class is either produced by a
    raise site, or is the family default of an exception type that IS
    raised (sites may narrow the class per condition, like the
    reference's DeltaErrors.scala factories), or the default of a base
    class whose subclasses are raised (e.g. ConcurrentModification)."""
    produced, raised_types, defaults = _produced_classes()
    family_defaults = {defaults[t] for t in raised_types
                       if t in defaults}
    # base classes of raised subclasses
    base_classes = set()
    for _n, obj in inspect.getmembers(E, inspect.isclass):
        if issubclass(obj, DeltaError) and obj.__name__ in raised_types:
            for parent in obj.__mro__[1:]:
                if parent is DeltaError or not issubclass(parent,
                                                          DeltaError):
                    break
                base_classes.add(parent.error_class)
    # classes the AST census cannot attribute to a raise site:
    # UnsupportedTableFeatureError picks its class inside __init__, and
    # MergeBuilder._validate_clauses raises through a data-driven loop
    # (error_class=ec) — covered by test_merge_clause_validation
    special = {
        "DELTA_UNSUPPORTED_FEATURES_FOR_WRITE",
        "DELTA_NON_LAST_MATCHED_CLAUSE_OMIT_CONDITION",
        "DELTA_NON_LAST_NOT_MATCHED_CLAUSE_OMIT_CONDITION",
        "DELTA_NON_LAST_NOT_MATCHED_BY_SOURCE_CLAUSE_OMIT_CONDITION",
    }
    ok = produced | family_defaults | base_classes | special | \
        {"DELTA_ERROR"}
    dead = sorted(set(error_catalog()) - ok)
    assert not dead, f"catalog entries no raise site can produce: {dead}"


def test_every_explicit_error_class_is_cataloged():
    """The inverse: every error_class= string used at a raise site (and
    every class default) exists in the catalog — no typo'd classes."""
    produced, _raised, defaults = _produced_classes()
    catalog = error_catalog()
    unknown = sorted((produced | set(defaults.values())) - set(catalog))
    assert not unknown, f"uncataloged error classes in use: {unknown}"


# ---- behavior tests for the round-5 validations ----------------------

def test_new_validation_conditions(tmp_path):
    """The genuinely-new checks added with their catalog classes."""
    import pyarrow as pa
    import pytest

    import delta_tpu.api as dta
    from delta_tpu.errors import DeltaError, error_info
    from delta_tpu.table import Table

    p = str(tmp_path / "t")
    dta.write_table(p, pa.table({"id": pa.array([1, 2], pa.int64())}))
    t = Table.for_path(p)

    def klass(fn):
        with __import__("pytest").raises(DeltaError) as ei:
            fn()
        return error_info(ei.value)["errorClass"]

    # CDC range start > end
    from delta_tpu.read.cdc import table_changes
    from delta_tpu.sql import sql

    sql(f"ALTER TABLE '{p}' SET TBLPROPERTIES "
        f"('delta.enableChangeDataFeed' = 'true')")
    assert klass(lambda: table_changes(t, 5, 1)) == "DELTA_INVALID_CDC_RANGE"

    # time travel: both version and timestamp
    assert klass(lambda: dta.read_table(p, version=0, timestamp_ms=1)) \
        == "DELTA_ONEOF_IN_TIMETRAVEL"

    # unset non-existent property
    from delta_tpu.commands.alter import unset_properties

    assert klass(lambda: unset_properties(t, ["delta.nope"])) \
        == "DELTA_UNSET_NON_EXISTENT_PROPERTY"

    # invalid characters in column names without column mapping
    assert klass(lambda: dta.write_table(
        str(tmp_path / "bad"), pa.table({"a b": [1]}))) \
        == "DELTA_INVALID_CHARACTERS_IN_COLUMN_NAME"

    # non-boolean CHECK constraint
    from delta_tpu.constraints import add_constraint

    assert klass(lambda: add_constraint(t, "c1", "id")) \
        == "DELTA_NON_BOOLEAN_CHECK_CONSTRAINT"

    # malformed interval table property
    from delta_tpu.config import _parse_interval_ms

    assert klass(lambda: _parse_interval_ms("interval five days")) \
        == "DELTA_INVALID_INTERVAL"
    assert klass(lambda: _parse_interval_ms("interval")) \
        == "DELTA_INVALID_CALENDAR_INTERVAL_EMPTY"

    # reserved CDC column names on write
    assert klass(lambda: dta.write_table(
        p, pa.table({"id": [3], "_change_type": ["x"]}), mode="append")) \
        == "RESERVED_CDC_COLUMNS_ON_WRITE"


def test_error_info_subclassed_iceberg_compat(tmp_path):
    """Dotted subclass keys (the reference's errorClass.subClass shape)
    resolve through error_info."""
    from delta_tpu.errors import error_catalog

    entry = error_catalog()[
        "DELTA_ICEBERG_COMPAT_VIOLATION.DELETION_VECTORS_SHOULD_BE_DISABLED"]
    assert entry["sqlState"]


def test_invalid_column_chars_nested_and_alter(tmp_path):
    """The name-character rule holds at every schema change (the
    update_metadata choke point), including nested struct fields and
    ALTER ADD COLUMNS — not just top-level creation."""
    import pyarrow as pa
    import pytest

    import delta_tpu.api as dta
    from delta_tpu.commands.alter import add_columns
    from delta_tpu.errors import DeltaError, error_info
    from delta_tpu.models.schema import LONG, StructField
    from delta_tpu.table import Table

    # nested struct child with a bad name
    p1 = str(tmp_path / "nested")
    nested = pa.table({"s": pa.array([{"a b": 1}],
                                     pa.struct([("a b", pa.int64())]))})
    with pytest.raises(DeltaError) as ei:
        dta.write_table(p1, nested)
    assert error_info(ei.value)["errorClass"] == \
        "DELTA_INVALID_CHARACTERS_IN_COLUMN_NAME"

    # ALTER ADD COLUMNS with a bad name on an existing table
    p2 = str(tmp_path / "plain")
    dta.write_table(p2, pa.table({"id": pa.array([1], pa.int64())}))
    with pytest.raises(DeltaError) as ei:
        add_columns(Table.for_path(p2), [StructField("a b", LONG)])
    assert error_info(ei.value)["errorClass"] == \
        "DELTA_INVALID_CHARACTERS_IN_COLUMN_NAME"

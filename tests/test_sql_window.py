"""Window-function semantics, one named test per function family
(VERDICT r4 ask #9), each run on BOTH substrates — the TpuEngine
device spine (`ops/sqlops.py` window kernels) and the HostEngine
pandas path.

Families: partition-only aggregates, whole-frame windows, the SQL
default running RANGE frame (peer sharing) for sum/avg/min/max/count,
explicit ROWS frames, rank/row_number/dense_rank (ties, partitions,
multi-key order), null ordering per key (Spark: NULLS FIRST asc,
NULLS LAST desc), nulls in aggregated values, windows over aggregated
results, and the error paths. The TPC-DS windowed queries
(q47/q51/q53/q57/q63/q89...) are oracle-validated end-to-end in
test_tpcds.py; these pin the primitive semantics."""

import numpy as np
import pyarrow as pa
import pytest

import delta_tpu.api as dta
from delta_tpu.errors import DeltaError
from delta_tpu.sql import sql as _sql


@pytest.fixture(params=["device", "host"])
def eng(request):
    if request.param == "device":
        from delta_tpu.engine.tpu import TpuEngine

        return TpuEngine()
    from delta_tpu.engine.host import HostEngine

    return HostEngine()


@pytest.fixture
def path(tmp_table_path):
    dta.write_table(tmp_table_path, pa.table({
        "g": pa.array(["a", "a", "a", "b", "b"]),
        "o": pa.array([1, 2, 2, 1, 2], pa.int64()),
        "v": pa.array([10.0, 20.0, 30.0, 5.0, 7.0]),
    }))
    return tmp_table_path


@pytest.fixture
def nullpath(tmp_table_path):
    dta.write_table(tmp_table_path, pa.table({
        "g": pa.array(["a", "a", "a", "b", "b", "b"]),
        "o": pa.array([1, None, 3, None, 2, 1], pa.int64()),
        "v": pa.array([10.0, 20.0, None, 5.0, None, 7.0]),
    }))
    return tmp_table_path


# ---- partition / whole-frame aggregates -----------------------------

def test_partition_aggregate(path, eng):
    out = _sql(f"SELECT g, v, sum(v) OVER (PARTITION BY g) t "
               f"FROM '{path}' ORDER BY g, o, v", engine=eng)
    assert out.column("t").to_pylist() == [60.0, 60.0, 60.0, 12.0, 12.0]


@pytest.mark.parametrize("fn,expect_a,expect_b", [
    ("min", 10.0, 5.0), ("max", 30.0, 7.0), ("avg", 20.0, 6.0),
])
def test_partition_min_max_avg(path, eng, fn, expect_a, expect_b):
    out = _sql(f"SELECT g, {fn}(v) OVER (PARTITION BY g) t "
               f"FROM '{path}' ORDER BY g, o, v", engine=eng)
    assert out.column("t").to_pylist() == [expect_a] * 3 + [expect_b] * 2


def test_partition_count_skips_nulls(nullpath, eng):
    out = _sql(f"SELECT g, count(v) OVER (PARTITION BY g) c "
               f"FROM '{nullpath}' ORDER BY g", engine=eng)
    assert out.column("c").to_pylist() == [2, 2, 2, 2, 2, 2]


def test_partition_count_star(path, eng):
    out = _sql(f"SELECT g, count(*) OVER (PARTITION BY g) c "
               f"FROM '{path}' ORDER BY g, o, v", engine=eng)
    assert out.column("c").to_pylist() == [3, 3, 3, 2, 2]


def test_whole_frame_window(path, eng):
    out = _sql(f"SELECT v, avg(v) OVER () a FROM '{path}' ORDER BY v",
               engine=eng)
    assert out.column("a").to_pylist() == [14.4] * 5


def test_partition_sum_all_null_is_null(nullpath, eng):
    # SQL: SUM over only NULLs is NULL (both substrates agree)
    out = _sql(f"SELECT o, sum(v) OVER (PARTITION BY o) s "
               f"FROM '{nullpath}' WHERE o = 3", engine=eng)
    assert out.column("s").to_pylist() == [None]


# ---- running frames (ORDER BY in the window) ------------------------

def test_running_sum_range_frame(path, eng):
    # ORDER BY without explicit frame = RANGE UNBOUNDED..CURRENT ROW:
    # order-key peers share the value at their last peer row
    out = _sql(f"SELECT o, sum(v) OVER (PARTITION BY g ORDER BY o) c "
               f"FROM '{path}' ORDER BY g, o, v", engine=eng)
    assert out.column("c").to_pylist() == [10.0, 60.0, 60.0, 5.0, 12.0]


def test_running_rows_frame_no_peer_sharing(path, eng):
    out = _sql(
        f"SELECT o, sum(v) OVER (PARTITION BY g ORDER BY o, v "
        f"ROWS BETWEEN UNBOUNDED PRECEDING AND CURRENT ROW) c "
        f"FROM '{path}' ORDER BY g, o, v", engine=eng)
    assert out.column("c").to_pylist() == [10.0, 30.0, 60.0, 5.0, 12.0]


@pytest.mark.parametrize("fn,expect", [
    ("min", [10.0, 10.0, 10.0, 5.0, 5.0]),
    ("max", [10.0, 30.0, 30.0, 5.0, 7.0]),
    ("count", [1, 3, 3, 1, 2]),
])
def test_running_min_max_count(path, eng, fn, expect):
    out = _sql(f"SELECT o, {fn}(v) OVER (PARTITION BY g ORDER BY o) c "
               f"FROM '{path}' ORDER BY g, o, v", engine=eng)
    assert out.column("c").to_pylist() == expect


def test_running_avg(path, eng):
    out = _sql(f"SELECT o, avg(v) OVER (PARTITION BY g ORDER BY o) c "
               f"FROM '{path}' ORDER BY g, o, v", engine=eng)
    assert out.column("c").to_pylist() == [10.0, 20.0, 20.0, 5.0, 6.0]


def test_running_without_partition(path, eng):
    out = _sql(f"SELECT v, sum(v) OVER (ORDER BY v) c "
               f"FROM '{path}' ORDER BY v", engine=eng)
    assert out.column("c").to_pylist() == [5.0, 12.0, 22.0, 42.0, 72.0]


def test_running_null_values_carry(nullpath, eng):
    # NULL values don't contribute but the running value carries
    out = _sql(f"SELECT o, sum(v) OVER (PARTITION BY g ORDER BY o) c "
               f"FROM '{nullpath}' WHERE g = 'b' AND o IS NOT NULL "
               f"ORDER BY o", engine=eng)
    assert out.column("c").to_pylist() == [7.0, 7.0]


# ---- rank family ----------------------------------------------------

def test_rank_and_row_number(path, eng):
    out = _sql(f"SELECT g, v, "
               f"rank() OVER (PARTITION BY g ORDER BY v DESC) r "
               f"FROM '{path}' ORDER BY g, v", engine=eng)
    assert out.column("r").to_pylist() == [3, 2, 1, 2, 1]
    out = _sql(f"SELECT o, row_number() OVER (ORDER BY o) rn "
               f"FROM '{path}' WHERE g = 'a' ORDER BY o, rn",
               engine=eng)
    assert out.column("rn").to_pylist() == [1, 2, 3]


def test_rank_ties_share_min_position(tmp_table_path, eng):
    dta.write_table(tmp_table_path, pa.table({
        "v": pa.array([1, 2, 2, 3], pa.int64()),
    }))
    out = _sql(f"SELECT v, rank() OVER (ORDER BY v) r, "
               f"dense_rank() OVER (ORDER BY v) d "
               f"FROM '{tmp_table_path}' ORDER BY v", engine=eng)
    assert out.column("r").to_pylist() == [1, 2, 2, 4]
    assert out.column("d").to_pylist() == [1, 2, 2, 3]


def test_dense_rank_with_partitions(path, eng):
    out = _sql(f"SELECT g, o, dense_rank() OVER "
               f"(PARTITION BY g ORDER BY o) d "
               f"FROM '{path}' ORDER BY g, o, v", engine=eng)
    assert out.column("d").to_pylist() == [1, 2, 2, 1, 2]


def test_rank_multi_key_order(path, eng):
    out = _sql(f"SELECT g, o, v, row_number() OVER "
               f"(PARTITION BY g ORDER BY o ASC, v DESC) rn "
               f"FROM '{path}' ORDER BY g, o, v", engine=eng)
    # within g='a': (1,10)->1, (2,30)->2, (2,20)->3
    assert out.column("rn").to_pylist() == [1, 3, 2, 1, 2]


def test_rank_larger_scale_parity(tmp_table_path):
    # device vs host on 10k rows with ties — catches boundary bugs
    # the 5-row fixtures can't
    from delta_tpu.engine.host import HostEngine
    from delta_tpu.engine.tpu import TpuEngine

    rng = np.random.default_rng(3)
    n = 10_000
    dta.write_table(tmp_table_path, pa.table({
        "p": pa.array(rng.integers(0, 50, n), pa.int64()),
        "k": pa.array(rng.integers(0, 30, n), pa.int64()),
    }))
    q = (f"SELECT p, k, rank() OVER (PARTITION BY p ORDER BY k) r, "
         f"dense_rank() OVER (PARTITION BY p ORDER BY k) d, "
         f"row_number() OVER (PARTITION BY p ORDER BY k) rn "
         f"FROM '{tmp_table_path}' ORDER BY p, k, rn")
    a = _sql(q, engine=TpuEngine())
    b = _sql(q, engine=HostEngine())
    assert a.column("r").to_pylist() == b.column("r").to_pylist()
    assert a.column("d").to_pylist() == b.column("d").to_pylist()
    assert a.column("rn").to_pylist() == b.column("rn").to_pylist()


# ---- null ordering per key (Spark rule) -----------------------------

def test_window_order_nulls_first_asc(nullpath, eng):
    # Spark: ascending ORDER BY puts NULLs FIRST -> they rank 1
    out = _sql(f"SELECT g, o, row_number() OVER "
               f"(PARTITION BY g ORDER BY o) rn "
               f"FROM '{nullpath}' WHERE g = 'b' ORDER BY rn",
               engine=eng)
    assert out.column("o").to_pylist() == [None, 1, 2]
    assert out.column("rn").to_pylist() == [1, 2, 3]


def test_window_order_nulls_last_desc(nullpath, eng):
    out = _sql(f"SELECT g, o, row_number() OVER "
               f"(PARTITION BY g ORDER BY o DESC) rn "
               f"FROM '{nullpath}' WHERE g = 'b' ORDER BY rn",
               engine=eng)
    assert out.column("o").to_pylist() == [2, 1, None]
    assert out.column("rn").to_pylist() == [1, 2, 3]


def test_rank_null_keys_are_peers(nullpath, eng):
    # two NULL order keys in one partition tie (rank peers)
    out = _sql(f"SELECT rank() OVER (ORDER BY v) r FROM '{nullpath}' "
               f"WHERE g = 'b' ORDER BY r", engine=eng)
    assert out.column("r").to_pylist() == [1, 2, 3]


# ---- windows over aggregates / string partitions --------------------

def test_window_over_aggregate(path, eng):
    # q12/q98 shape: sum(sum(x)) over (partition by ...)
    out = _sql(f"SELECT g, o, sum(v) s, "
               f"sum(v)*100/sum(sum(v)) OVER (PARTITION BY g) pct "
               f"FROM '{path}' GROUP BY g, o ORDER BY g, o",
               engine=eng)
    pct = out.column("pct").to_pylist()
    assert pct[0] == pytest.approx(100 * 10 / 60)
    assert pct[1] == pytest.approx(100 * 50 / 60)


def test_window_order_by_string_key(path, eng):
    out = _sql(f"SELECT g, row_number() OVER (ORDER BY g DESC, o, v) rn "
               f"FROM '{path}' ORDER BY rn", engine=eng)
    assert out.column("g").to_pylist() == ["b", "b", "a", "a", "a"]


# ---- error paths ----------------------------------------------------

def test_distinct_in_window_rejected(path, eng):
    with pytest.raises(DeltaError, match="DISTINCT"):
        _sql(f"SELECT count(DISTINCT v) OVER (PARTITION BY g) "
             f"FROM '{path}'", engine=eng)


def test_window_rank_requires_order(path, eng):
    with pytest.raises(DeltaError, match="ORDER BY"):
        _sql(f"SELECT rank() OVER (PARTITION BY g) FROM '{path}'",
             engine=eng)


def test_partition_int_sum_keeps_int_schema(tmp_table_path):
    # int64 in -> int64 out on BOTH substrates (schema parity)
    from delta_tpu.engine.host import HostEngine
    from delta_tpu.engine.tpu import TpuEngine

    dta.write_table(tmp_table_path, pa.table({
        "g": pa.array([0, 0, 1], pa.int64()),
        "i": pa.array([1, 2, 3], pa.int64()),
    }))
    q = (f"SELECT g, sum(i) OVER (PARTITION BY g) s, "
         f"min(i) OVER (PARTITION BY g) m FROM '{tmp_table_path}' "
         f"ORDER BY g, i")
    a = _sql(q, engine=TpuEngine())
    b = _sql(q, engine=HostEngine())
    assert a.schema.field("s").type == b.schema.field("s").type
    assert a.schema.field("m").type == b.schema.field("m").type
    assert a.column("s").to_pylist() == [3, 3, 3]
    assert a.column("m").to_pylist() == [1, 1, 3]

"""Window-function semantics (round-4 sqlengine surface).

Partition-only aggregates, the SQL default running RANGE frame when
ORDER BY is present, rank/row_number/dense_rank, and windows over
aggregated results (the TPC-DS q12/q53/q98 shapes — those queries are
oracle-validated end-to-end in test_tpcds.py; these pin the primitive
semantics)."""

import pyarrow as pa
import pytest

import delta_tpu.api as dta
from delta_tpu.errors import DeltaError
from delta_tpu.sql import sql


@pytest.fixture
def path(tmp_table_path):
    dta.write_table(tmp_table_path, pa.table({
        "g": pa.array(["a", "a", "a", "b", "b"]),
        "o": pa.array([1, 2, 2, 1, 2], pa.int64()),
        "v": pa.array([10.0, 20.0, 30.0, 5.0, 7.0]),
    }))
    return tmp_table_path


def test_partition_aggregate(path):
    out = sql(f"SELECT g, v, sum(v) OVER (PARTITION BY g) t "
              f"FROM '{path}' ORDER BY g, o, v")
    assert out.column("t").to_pylist() == [60.0, 60.0, 60.0, 12.0, 12.0]


def test_whole_frame_window(path):
    out = sql(f"SELECT v, avg(v) OVER () a FROM '{path}' ORDER BY v")
    assert out.column("a").to_pylist() == [14.4] * 5


def test_running_sum_range_frame(path):
    # ORDER BY without explicit frame = RANGE UNBOUNDED..CURRENT ROW:
    # order-key peers share the value at their last peer row
    out = sql(f"SELECT o, sum(v) OVER (PARTITION BY g ORDER BY o) c "
              f"FROM '{path}' ORDER BY g, o, v")
    assert out.column("c").to_pylist() == [10.0, 60.0, 60.0, 5.0, 12.0]


def test_rank_and_row_number(path):
    out = sql(f"SELECT g, v, "
              f"rank() OVER (PARTITION BY g ORDER BY v DESC) r "
              f"FROM '{path}' ORDER BY g, v")
    assert out.column("r").to_pylist() == [3, 2, 1, 2, 1]
    out = sql(f"SELECT o, row_number() OVER (ORDER BY o) rn "
              f"FROM '{path}' WHERE g = 'a' ORDER BY o, rn")
    assert out.column("rn").to_pylist() == [1, 2, 3]


def test_rank_ties_share_min_position(tmp_table_path):
    dta.write_table(tmp_table_path, pa.table({
        "v": pa.array([1, 2, 2, 3], pa.int64()),
    }))
    out = sql(f"SELECT v, rank() OVER (ORDER BY v) r, "
              f"dense_rank() OVER (ORDER BY v) d "
              f"FROM '{tmp_table_path}' ORDER BY v")
    assert out.column("r").to_pylist() == [1, 2, 2, 4]
    assert out.column("d").to_pylist() == [1, 2, 2, 3]


def test_window_over_aggregate(path):
    # q12/q98 shape: sum(sum(x)) over (partition by ...)
    out = sql(f"SELECT g, o, sum(v) s, "
              f"sum(v)*100/sum(sum(v)) OVER (PARTITION BY g) pct "
              f"FROM '{path}' GROUP BY g, o ORDER BY g, o")
    pct = out.column("pct").to_pylist()
    assert pct[0] == pytest.approx(100 * 10 / 60)
    assert pct[1] == pytest.approx(100 * 50 / 60)


def test_distinct_in_window_rejected(path):
    with pytest.raises(DeltaError, match="DISTINCT"):
        sql(f"SELECT count(DISTINCT v) OVER (PARTITION BY g) "
            f"FROM '{path}'")


def test_window_rank_requires_order(path):
    with pytest.raises(DeltaError, match="ORDER BY"):
        sql(f"SELECT rank() OVER (PARTITION BY g) FROM '{path}'")

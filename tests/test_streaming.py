import numpy as np
import pyarrow as pa
import pytest

import delta_tpu.api as dta
from delta_tpu.errors import DeltaError
from delta_tpu.streaming import DeltaSink, DeltaSource, DeltaSourceOffset, ReadLimits
from delta_tpu.table import Table


def _batch(start, n):
    return pa.table(
        {
            "id": pa.array(np.arange(start, start + n, dtype=np.int64)),
            "v": pa.array(np.full(n, float(start))),
        }
    )


def test_sink_exactly_once(tmp_table_path):
    sink = DeltaSink(tmp_table_path, query_id="q1")
    v0 = sink.add_batch(0, _batch(0, 10))
    assert v0 == 0
    v1 = sink.add_batch(1, _batch(10, 10))
    assert v1 == 1
    # replay of batch 1 must be a no-op
    assert sink.add_batch(1, _batch(10, 10)) is None
    assert sink.add_batch(0, _batch(0, 10)) is None
    out = dta.read_table(tmp_table_path)
    assert out.num_rows == 20
    # a different query id is independent
    sink2 = DeltaSink(tmp_table_path, query_id="q2")
    assert sink2.add_batch(0, _batch(100, 5)) is not None


def test_source_initial_snapshot_then_tail(tmp_table_path):
    dta.write_table(tmp_table_path, _batch(0, 10))
    dta.write_table(tmp_table_path, _batch(10, 10))
    table = Table.for_path(tmp_table_path)
    src = DeltaSource(table)
    off1 = src.latest_offset(None)
    assert off1 is not None and off1.is_initial_snapshot
    batch1 = src.get_batch(None, off1)
    assert batch1.num_rows == 20  # initial snapshot covers both commits
    # nothing new
    assert src.latest_offset(off1) == off1
    # append arrives
    dta.write_table(tmp_table_path, _batch(20, 5))
    off2 = src.latest_offset(off1)
    assert off2 != off1 and not off2.is_initial_snapshot
    batch2 = src.get_batch(off1, off2)
    assert batch2.num_rows == 5
    assert sorted(batch2.column("id").to_pylist()) == list(range(20, 25))


def test_source_rate_limit(tmp_table_path):
    for i in range(4):
        dta.write_table(tmp_table_path, _batch(i * 10, 10))
    table = Table.for_path(tmp_table_path)
    src = DeltaSource(table, starting_version=0)
    limits = ReadLimits(max_files=2)
    offsets = []
    rows = 0
    cur = None
    for off, batch in src.micro_batches(limits=limits):
        offsets.append(off)
        rows += batch.num_rows
    assert rows == 40
    assert len(offsets) == 2  # 4 files admitted 2 per batch


def test_source_starting_version(tmp_table_path):
    dta.write_table(tmp_table_path, _batch(0, 10))
    dta.write_table(tmp_table_path, _batch(10, 10))
    dta.write_table(tmp_table_path, _batch(20, 10))
    table = Table.for_path(tmp_table_path)
    src = DeltaSource(table, starting_version=1)
    off = src.latest_offset(None)
    batch = src.get_batch(None, off)
    assert sorted(batch.column("id").to_pylist()) == list(range(10, 30))


def test_source_rejects_deletes(tmp_table_path):
    from delta_tpu.commands.dml import delete
    from delta_tpu.expressions import col, lit

    dta.write_table(tmp_table_path, _batch(0, 10))
    table = Table.for_path(tmp_table_path)
    delete(table, col("id") < lit(5))
    src = DeltaSource(table, starting_version=0)
    with pytest.raises(DeltaError):
        src.latest_offset(None)
    src2 = DeltaSource(table, starting_version=0, ignore_changes=True)
    assert src2.latest_offset(None) is not None


def test_offset_json_roundtrip():
    off = DeltaSourceOffset(7, 3, True)
    assert DeltaSourceOffset.from_json(off.to_json()) == off

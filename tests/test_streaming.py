import numpy as np
import pyarrow as pa
import pytest

import delta_tpu.api as dta
from delta_tpu.errors import DeltaError
from delta_tpu.streaming import DeltaSink, DeltaSource, DeltaSourceOffset, ReadLimits
from delta_tpu.table import Table


def _batch(start, n):
    return pa.table(
        {
            "id": pa.array(np.arange(start, start + n, dtype=np.int64)),
            "v": pa.array(np.full(n, float(start))),
        }
    )


def test_sink_exactly_once(tmp_table_path):
    sink = DeltaSink(tmp_table_path, query_id="q1")
    v0 = sink.add_batch(0, _batch(0, 10))
    assert v0 == 0
    v1 = sink.add_batch(1, _batch(10, 10))
    assert v1 == 1
    # replay of batch 1 must be a no-op
    assert sink.add_batch(1, _batch(10, 10)) is None
    assert sink.add_batch(0, _batch(0, 10)) is None
    out = dta.read_table(tmp_table_path)
    assert out.num_rows == 20
    # a different query id is independent
    sink2 = DeltaSink(tmp_table_path, query_id="q2")
    assert sink2.add_batch(0, _batch(100, 5)) is not None


def test_source_initial_snapshot_then_tail(tmp_table_path):
    dta.write_table(tmp_table_path, _batch(0, 10))
    dta.write_table(tmp_table_path, _batch(10, 10))
    table = Table.for_path(tmp_table_path)
    src = DeltaSource(table)
    off1 = src.latest_offset(None)
    assert off1 is not None and off1.is_initial_snapshot
    batch1 = src.get_batch(None, off1)
    assert batch1.num_rows == 20  # initial snapshot covers both commits
    # nothing new
    assert src.latest_offset(off1) == off1
    # append arrives
    dta.write_table(tmp_table_path, _batch(20, 5))
    off2 = src.latest_offset(off1)
    assert off2 != off1 and not off2.is_initial_snapshot
    batch2 = src.get_batch(off1, off2)
    assert batch2.num_rows == 5
    assert sorted(batch2.column("id").to_pylist()) == list(range(20, 25))


def test_source_rate_limit(tmp_table_path):
    for i in range(4):
        dta.write_table(tmp_table_path, _batch(i * 10, 10))
    table = Table.for_path(tmp_table_path)
    src = DeltaSource(table, starting_version=0)
    limits = ReadLimits(max_files=2)
    offsets = []
    rows = 0
    cur = None
    for off, batch in src.micro_batches(limits=limits):
        offsets.append(off)
        rows += batch.num_rows
    assert rows == 40
    assert len(offsets) == 2  # 4 files admitted 2 per batch


def test_source_starting_version(tmp_table_path):
    dta.write_table(tmp_table_path, _batch(0, 10))
    dta.write_table(tmp_table_path, _batch(10, 10))
    dta.write_table(tmp_table_path, _batch(20, 10))
    table = Table.for_path(tmp_table_path)
    src = DeltaSource(table, starting_version=1)
    off = src.latest_offset(None)
    batch = src.get_batch(None, off)
    assert sorted(batch.column("id").to_pylist()) == list(range(10, 30))


def test_source_rejects_deletes(tmp_table_path):
    from delta_tpu.commands.dml import delete
    from delta_tpu.expressions import col, lit

    dta.write_table(tmp_table_path, _batch(0, 10))
    table = Table.for_path(tmp_table_path)
    delete(table, col("id") < lit(5))
    src = DeltaSource(table, starting_version=0)
    with pytest.raises(DeltaError):
        src.latest_offset(None)
    src2 = DeltaSource(table, starting_version=0, ignore_changes=True)
    assert src2.latest_offset(None) is not None


def test_offset_json_roundtrip():
    off = DeltaSourceOffset(7, 3, True)
    assert DeltaSourceOffset.from_json(off.to_json()) == off


# ---------------------------------------------------------------- CDC source

def _cdf_table(path):
    dta.write_table(path, _batch(0, 10),
                    properties={"delta.enableChangeDataFeed": "true"})
    return Table.for_path(path)


def test_cdc_source_requires_cdf(tmp_table_path):
    from delta_tpu.streaming import DeltaCDCSource

    dta.write_table(tmp_table_path, _batch(0, 5))
    with pytest.raises(DeltaError):
        DeltaCDCSource(Table.for_path(tmp_table_path))


def test_cdc_source_initial_snapshot_then_changes(tmp_table_path):
    from delta_tpu.commands.dml import delete, update
    from delta_tpu.expressions import col, lit
    from delta_tpu.streaming import DeltaCDCSource

    table = _cdf_table(tmp_table_path)
    src = DeltaCDCSource(table)

    # batch 1: the initial snapshot as inserts
    off1 = src.latest_offset(None)
    assert off1.is_initial_snapshot
    b1 = src.get_batch(None, off1)
    assert b1.num_rows == 10
    assert set(b1.column("_change_type").to_pylist()) == {"insert"}
    assert set(b1.column("_commit_version").to_pylist()) == {0}

    # no new commits: offset unchanged
    assert src.latest_offset(off1) == off1

    # commits: an update (CDC files) and a delete
    update(table, {"v": lit(-1.0)}, col("id") == lit(3))  # v1
    delete(table, predicate=col("id") >= lit(8))          # v2

    off2 = src.latest_offset(off1)
    assert off2.reservoir_version == 2
    b2 = src.get_batch(off1, off2)
    types = b2.column("_change_type").to_pylist()
    vers = b2.column("_commit_version").to_pylist()
    assert "delete" in types
    # the update produced preimage/postimage rows via CDC files
    assert "update_preimage" in types and "update_postimage" in types
    assert set(vers) == {1, 2}


def test_cdc_source_starting_version_and_rate_limit(tmp_table_path):
    from delta_tpu.streaming import DeltaCDCSource, ReadLimits

    table = _cdf_table(tmp_table_path)
    dta.write_table(tmp_table_path, _batch(10, 10))  # v1
    dta.write_table(tmp_table_path, _batch(20, 10))  # v2
    dta.write_table(tmp_table_path, _batch(30, 10))  # v3

    # starting_version=1: no initial snapshot, tail from v1
    src = DeltaCDCSource(table, starting_version=1)
    lim = ReadLimits(max_files=1)  # one file per version here
    off = src.latest_offset(None, lim)
    assert off.reservoir_version == 1 and not off.is_initial_snapshot
    b = src.get_batch(None, off)
    assert sorted(b.column("id").to_pylist()) == list(range(10, 20))
    assert set(b.column("_change_type").to_pylist()) == {"insert"}

    # drain the rest one version at a time
    versions = []
    for o, batch in src.micro_batches(lim, start=off):
        versions.append(o.reservoir_version)
    assert versions == [2, 3]


def test_cdc_source_schema_consistency(tmp_table_path):
    """Initial-snapshot batches and empty batches carry the same CDC
    schema as change batches (_change_type/_commit_version/_commit_timestamp)."""
    from delta_tpu.streaming import DeltaCDCSource

    table = _cdf_table(tmp_table_path)
    src = DeltaCDCSource(table)
    off = src.latest_offset(None)
    b = src.get_batch(None, off)
    for c in ("_change_type", "_commit_version", "_commit_timestamp"):
        assert c in b.column_names, c
    # metadata-only commit: offset advances, batch is empty but schemad
    t2 = Table.for_path(tmp_table_path)
    txn = t2.create_transaction_builder().build()
    txn.set_operation_parameters({"properties": {}})
    txn.commit()
    off2 = src.latest_offset(off)
    assert off2.reservoir_version == 1
    b2 = src.get_batch(off, off2)
    assert b2.num_rows == 0
    for c in ("id", "_change_type", "_commit_timestamp"):
        assert c in b2.column_names, c


def test_cdc_source_schema_change_errors(tmp_table_path):
    from delta_tpu.commands.alter import add_columns
    from delta_tpu.models.schema import LONG, StructField
    from delta_tpu.streaming import DeltaCDCSource

    table = _cdf_table(tmp_table_path)
    src = DeltaCDCSource(table)
    off = src.latest_offset(None)
    add_columns(Table.for_path(tmp_table_path),
                [StructField("extra", LONG)])  # v1
    with pytest.raises(DeltaError, match="schema changed"):
        src.latest_offset(off)


def test_cdc_source_expired_commit_errors(tmp_table_path):
    import os
    from delta_tpu.streaming import DeltaCDCSource
    from delta_tpu.utils import filenames

    table = _cdf_table(tmp_table_path)
    src = DeltaCDCSource(table)
    off = src.latest_offset(None)
    dta.write_table(tmp_table_path, _batch(10, 5), mode="append")  # v1
    dta.write_table(tmp_table_path, _batch(20, 5), mode="append")  # v2
    # checkpoint v2 so the log stays loadable, then expire v1 as log
    # cleanup would
    table.checkpoint()
    os.unlink(filenames.delta_file(table.log_path, 1))
    with pytest.raises(DeltaError, match="expired"):
        src.latest_offset(off)


def test_cdc_source_schema_change_delivers_prior_commits_first(tmp_table_path):
    from delta_tpu.commands.alter import add_columns
    from delta_tpu.models.schema import LONG, StructField
    from delta_tpu.streaming import DeltaCDCSource

    table = _cdf_table(tmp_table_path)
    src = DeltaCDCSource(table)
    off0 = src.latest_offset(None)
    dta.write_table(tmp_table_path, _batch(10, 5), mode="append")  # v1: data
    add_columns(Table.for_path(tmp_table_path),
                [StructField("extra", LONG)])                      # v2: schema
    # v1 must be delivered under the old schema before the error fires
    off1 = src.latest_offset(off0)
    assert off1.reservoir_version == 1
    b = src.get_batch(off0, off1)
    assert sorted(b.column("id").to_pylist()) == list(range(10, 15))
    with pytest.raises(DeltaError, match="schema changed"):
        src.latest_offset(off1)


def test_source_expired_commit_errors(tmp_table_path):
    """Non-CDC DeltaSource shares the expiry guard: a resume offset
    pointing before cleaned-up commits must error, not stall."""
    import os
    from delta_tpu.utils import filenames

    dta.write_table(tmp_table_path, _batch(0, 10))
    table = Table.for_path(tmp_table_path)
    src = DeltaSource(table)
    off = src.latest_offset(None)
    dta.write_table(tmp_table_path, _batch(10, 5), mode="append")  # v1
    dta.write_table(tmp_table_path, _batch(20, 5), mode="append")  # v2
    table.checkpoint()
    os.unlink(filenames.delta_file(table.log_path, 1))
    with pytest.raises(DeltaError, match="expired"):
        src.latest_offset(off)

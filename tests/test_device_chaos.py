"""Device-fault chaos plane: seeded injection at the dispatch funnel,
route breakers with host-twin degradation, HBM shed-and-retry.

The storage half of the chaos story lives in test_resilience.py
(ChaosStore hammering the LogStore); this module soaks the device half:
a seeded :class:`ChaosEngine` armed at the
``obs/device.py::device_dispatch()`` funnel injects dispatch errors,
simulated RESOURCE_EXHAUSTED, transfer stalls, and recompile storms
into every gated device route (replay / parse / decode / skip / sql),
and the acceptance property is the same as the storage soak's: the
workload converges **bit-identically** to the fault-free run, because
every route classifies, counts, and falls back to its host twin instead
of corrupting or dying.

Everything runs on CPU (the conftest mesh emulates 8 devices) — the
gate economics still choose the device routes there, so the injection
exercises the real absorption paths, never mocks."""

import time

import numpy as np
import pyarrow as pa
import pytest

import delta_tpu.api as dta
from delta_tpu import obs, resilience
from delta_tpu.engine.tpu import TpuEngine
from delta_tpu.expressions import col, lit
from delta_tpu.obs import hbm
from delta_tpu.parallel import gate
from delta_tpu.resilience import device_faults
from delta_tpu.resilience.breaker import route_breaker_for
from delta_tpu.resilience.classify import TRANSIENT, classify
from delta_tpu.resilience.device_chaos import (
    ChaosEngine,
    DeviceChaosError,
    DeviceChaosSchedule,
    DeviceResourceExhaustedError,
    engine_from_env,
)
from delta_tpu.sql import sql
from delta_tpu.tables import Table

GATES = ("replay", "parse", "decode", "skip", "sql")


@pytest.fixture(autouse=True)
def _device_chaos_obs():
    """Gate records on, ledger accounting on, both swept per test.

    `resilience.reset()` (the conftest autouse fixture) already disarms
    any leftover chaos engine and clears the route breakers; this adds
    the obs planes the assertions below read."""
    obs.reset_device_obs()
    obs.reset_hbm_obs()
    obs.set_device_obs_mode("on")
    obs.set_hbm_obs_mode("on")
    yield
    obs.set_device_obs_mode(None)
    obs.set_hbm_obs_mode(None)
    obs.reset_device_obs()
    obs.reset_hbm_obs()


def _chaos(seed, **rates):
    """A chaos engine whose stalls cost no wall clock."""
    return ChaosEngine(DeviceChaosSchedule(seed, **rates),
                       sleep=lambda s: None)


def _drive(engine, n=40):
    """Deterministic dispatch sequence straight at the funnel hook."""
    for i in range(n):
        try:
            engine.on_dispatch(f"kern.{i % 3}", key=(i % 5,),
                               gate=GATES[i % 5])
        except DeviceChaosError:
            pass
    return list(engine.fault_log)


# ------------------------------------------------- schedule / engine


def test_schedule_replay_identical_fault_log():
    """The replayability contract: same seed + same dispatch sequence
    -> bit-identical fault schedule; a different seed diverges."""
    rates = dict(dispatch_error_rate=0.2, oom_rate=0.1,
                 stall_rate=0.1, recompile_rate=0.1)
    log_a = _drive(_chaos(7, **rates))
    log_b = _drive(_chaos(7, **rates))
    assert log_a == log_b
    assert log_a  # the schedule actually injected something
    assert log_a != _drive(_chaos(8, **rates))


def test_fault_counts_mirror_log_and_counter():
    before = obs.counter("chaos.device_faults").value
    eng = _chaos(3, dispatch_error_rate=0.3, oom_rate=0.2)
    log = _drive(eng)
    assert eng.total_faults == len(log)
    assert sum(eng.fault_counts.values()) == len(log)
    assert eng.fault_counts["error"] == sum(
        1 for k, _, _ in log if k == "error")
    assert obs.counter("chaos.device_faults").value == before + len(log)


def test_context_manager_arms_the_dispatch_funnel():
    """Arming injects at the real `obs.device_dispatch` seam; exiting
    the context restores clean dispatch."""
    with _chaos(1, dispatch_error_rate=1.0) as eng:
        with pytest.raises(DeviceChaosError):
            with obs.device_dispatch("probe.kernel", key=(8,), gate="sql"):
                pass
    assert eng.fault_log == [("error", "probe.kernel", "sql")]
    with obs.device_dispatch("probe.kernel", key=(8,), gate="sql"):
        pass  # disarmed: no injection


def test_injection_works_with_device_obs_off():
    """The funnel hook runs before the obs-mode check: chaos does not
    require the observability plane."""
    obs.set_device_obs_mode("off")
    with _chaos(2, dispatch_error_rate=1.0):
        with pytest.raises(DeviceChaosError):
            with obs.device_dispatch("probe.kernel", gate="skip"):
                pass


def test_resilience_reset_disarms():
    eng = _chaos(1, dispatch_error_rate=1.0)
    eng.arm()
    resilience.reset()
    with obs.device_dispatch("probe.kernel", gate="sql"):
        pass  # no injection: reset() cleared the armed engine


def test_kernel_filter_scopes_injection():
    eng = _chaos(5, dispatch_error_rate=1.0)
    eng.kernel_filter = lambda name: name.startswith("sqlops.")
    with eng:
        with obs.device_dispatch("replay.single_raw", gate="replay"):
            pass  # filtered out: untouched
        with pytest.raises(DeviceChaosError):
            with obs.device_dispatch("sqlops.sort", gate="sql"):
                pass
    assert [k for k, _, _ in eng.fault_log] == ["error"]


def test_recompile_injection_salts_key_and_counts_compiles():
    """A recompile injection makes the SAME shape key read as novel, so
    device obs counts a compile per injection — the storm alarm's input
    — without touching the jit cache."""
    before = obs.counter("device.compiles").value
    with _chaos(9, recompile_rate=1.0) as eng:
        for _ in range(3):
            with obs.device_dispatch("probe.kernel", key=(4, 4),
                                     gate="decode"):
                pass
    assert eng.fault_counts["recompile"] == 3
    # every dispatch compiled: the salt made each key a first sighting
    assert obs.counter("device.compiles").value == before + 3


def test_stall_injection_sleeps_but_never_raises():
    naps = []
    eng = ChaosEngine(
        DeviceChaosSchedule(4, stall_rate=1.0, stall_s=(0.01, 0.02)),
        sleep=naps.append)
    with eng:
        with obs.device_dispatch("probe.kernel", gate="parse"):
            pass
    assert len(naps) == 1
    assert 0.01 <= naps[0] <= 0.02
    assert eng.fault_counts["stall"] == 1


def test_injected_faults_classify_transient():
    """Both injected fault shapes must classify transient — that is
    what licenses the absorption paths to run the host twin."""
    assert classify(DeviceChaosError("injected")) == TRANSIENT
    oom = DeviceResourceExhaustedError("sqlops.sort")
    assert classify(oom) == TRANSIENT
    assert device_faults.is_resource_exhausted(oom)
    assert "RESOURCE_EXHAUSTED" in str(oom)
    assert not device_faults.is_resource_exhausted(ValueError("nope"))


def test_engine_from_env(monkeypatch):
    monkeypatch.setenv("DELTA_TPU_DEVICE_CHAOS", "off")
    assert engine_from_env() is None
    monkeypatch.setenv("DELTA_TPU_DEVICE_CHAOS", "17")
    monkeypatch.setenv("DELTA_TPU_DEVICE_CHAOS_RATE", "0.25")
    monkeypatch.setenv("DELTA_TPU_DEVICE_CHAOS_KINDS", "error,stall")
    eng = engine_from_env()
    assert eng is not None
    s = eng.schedule
    assert s.seed == 17
    assert s.dispatch_error_rate == 0.25
    assert s.stall_rate == 0.25
    assert s.oom_rate == 0.0 and s.recompile_rate == 0.0


# ------------------------------------------------- HBM shed-and-retry


class _Artifact:
    """A weakref-able owner whose evictor releases its handle."""

    def __init__(self, cost):
        arr = np.zeros(64, dtype=np.int64)
        self.handle = hbm.register(
            self, kind="test-artifact", table_path=f"/t/{cost}",
            nbytes=arr.nbytes, rebuild_cost_class=cost)
        self.evicted = False
        self.handle._evictor = hbm._wrap_evictor(self.evict)

    def evict(self):
        self.evicted = True
        self.handle.release()


def test_shed_evicts_cheapest_to_rebuild_first():
    exp = _Artifact("expensive")
    cheap = _Artifact("cheap")
    norm = _Artifact("normal")
    n, freed = hbm.shed(max_artifacts=1)
    assert (n, freed) == (1, 512)
    assert cheap.evicted and not norm.evicted and not exp.evicted
    n, _ = hbm.shed(max_artifacts=2)
    assert n == 2
    assert norm.evicted and exp.evicted
    assert hbm.ledger().artifact_count() == 0
    assert not hbm.leak_records()


def test_shed_cap_env_knob(monkeypatch):
    monkeypatch.setenv("DELTA_TPU_HBM_SHED_MAX", "1")
    arts = [_Artifact("normal") for _ in range(3)]
    n, _ = hbm.shed()
    assert n == 1
    assert sum(a.evicted for a in arts) == 1


def test_shed_skips_artifacts_without_evictor():
    arr = np.zeros(8, dtype=np.int64)
    owner = _Artifact("cheap")
    pinned = hbm.register(owner, kind="pinned", table_path="/t/p",
                          nbytes=arr.nbytes)  # no evictor: unsheddable
    n, _ = hbm.shed(max_artifacts=8)
    assert n == 1  # only the evictable one went
    assert hbm.ledger().artifact_count() == 1
    pinned.release()


def test_shed_retry_evicts_and_retries_once():
    art = _Artifact("cheap")
    before = obs.counter("hbm.shed_retries").value
    calls = []

    def thunk():
        calls.append(1)
        if len(calls) == 1:
            raise DeviceResourceExhaustedError("sqlops.group_codes")
        return "answer"

    assert device_faults.shed_retry("sql", thunk) == "answer"
    assert len(calls) == 2
    assert art.evicted
    assert obs.counter("hbm.shed_retries").value == before + 1
    assert obs.counter("hbm.sheds").value >= 1


def test_shed_retry_nothing_sheddable_propagates():
    """Empty ledger: the allocation failure goes straight to the
    absorption path (host twin), no blind second attempt."""
    calls = []

    def thunk():
        calls.append(1)
        raise DeviceResourceExhaustedError("sqlops.sort")

    with pytest.raises(DeviceResourceExhaustedError):
        device_faults.shed_retry("sql", thunk)
    assert len(calls) == 1


def test_shed_retry_non_oom_errors_pass_through():
    art = _Artifact("cheap")

    def thunk():
        raise DeviceChaosError("not an allocation failure")

    with pytest.raises(DeviceChaosError):
        device_faults.shed_retry("sql", thunk)
    assert not art.evicted  # shed is reserved for allocation pressure
    art.evict()


def test_shed_noop_when_ledger_off():
    obs.set_hbm_obs_mode("off")
    assert hbm.shed() == (0, 0)


# --------------------------------------------- route breakers / gate


def _trip_sql(threshold):
    for _ in range(threshold):
        verdict = gate.route_failed("sql", DeviceChaosError("injected"))
        assert verdict == TRANSIENT


def _sql_decision():
    """One economics-scale sql_route decision (device-profitable)."""
    return gate.sql_route("group-agg", 200_000, nbytes=1_600_000,
                          engine_enabled=True)


def test_route_breaker_trips_and_degrades_decisions(monkeypatch):
    monkeypatch.setenv("DELTA_TPU_ROUTE_BREAKER_THRESHOLD", "2")
    resilience.reset()  # re-read the knob on next breaker creation
    assert _sql_decision() == "device"  # healthy: economics picks device
    before = obs.counter("gate.route_breaker_degrades").value
    _trip_sql(2)
    assert route_breaker_for("sql").state == "open"
    assert _sql_decision() == "host"
    rec = obs.get_gate_records()[-1]
    assert rec["reason"] == "breaker-open"
    assert obs.counter("gate.route_breaker_degrades").value == before + 1
    # the shared registry exposes it (serve /health renders this map)
    assert resilience.breaker_states()["route:sql"]["state"] == "open"


def test_route_breaker_permanent_failures_never_trip(monkeypatch):
    monkeypatch.setenv("DELTA_TPU_ROUTE_BREAKER_THRESHOLD", "2")
    resilience.reset()
    for _ in range(6):
        assert gate.route_failed(
            "sql", FileNotFoundError("part gone")) != TRANSIENT
    assert route_breaker_for("sql").state == "closed"
    assert _sql_decision() == "device"


def test_route_breaker_half_open_probe_rearms(monkeypatch):
    monkeypatch.setenv("DELTA_TPU_ROUTE_BREAKER_THRESHOLD", "1")
    monkeypatch.setenv("DELTA_TPU_ROUTE_BREAKER_RESET_S", "30")
    resilience.reset()
    _trip_sql(1)
    b = route_breaker_for("sql")
    assert b.state == "open"
    assert _sql_decision() == "host"
    # cooldown elapses (virtual clock: no wall waiting)
    now = [time.monotonic() + 31.0]
    b._clock = lambda: now[0]
    assert _sql_decision() == "device"
    assert obs.get_gate_records()[-1]["reason"] == "breaker-probe"
    # while the probe is in flight, further decisions stay degraded
    assert _sql_decision() == "host"
    gate.route_ok("sql")  # the probe's caller reports success
    assert b.state == "closed"
    assert _sql_decision() == "device"
    assert obs.get_gate_records()[-1]["reason"] == "economics"


def test_route_breaker_probe_failure_reopens(monkeypatch):
    monkeypatch.setenv("DELTA_TPU_ROUTE_BREAKER_THRESHOLD", "1")
    monkeypatch.setenv("DELTA_TPU_ROUTE_BREAKER_RESET_S", "30")
    resilience.reset()
    _trip_sql(1)
    b = route_breaker_for("sql")
    now = [time.monotonic() + 31.0]
    b._clock = lambda: now[0]
    assert _sql_decision() == "device"  # the probe
    gate.route_failed("sql", DeviceChaosError("probe failed"))
    assert b.state == "open"
    assert _sql_decision() == "host"  # clock restarted at the failure


def test_env_forced_routes_outrank_the_breaker(monkeypatch):
    """`DELTA_TPU_DEVICE_SQL=force` is explicit operator intent: the
    breaker must not silently override it."""
    monkeypatch.setenv("DELTA_TPU_ROUTE_BREAKER_THRESHOLD", "1")
    resilience.reset()
    _trip_sql(1)
    assert route_breaker_for("sql").state == "open"
    monkeypatch.setenv("DELTA_TPU_DEVICE_SQL", "force")
    assert _sql_decision() == "device"


# ------------------------------------------------------- chaos soak


def _engine():
    """A TpuEngine with every gated route opted in (on CPU the
    accel-backend default leaves parse/decode/skip off)."""
    eng = TpuEngine()
    eng.use_device_parse = True
    eng.use_device_decode = True
    eng.use_device_skip = True
    eng.use_device_sql = True
    return eng


def _batch(start, n):
    x = np.arange(start, start + n, dtype=np.int64)
    return pa.table({"x": x, "g": x % 7})


def _workload(eng, path):
    """Drive all five gated routes end to end: replay (snapshot
    builds), parse (json log tail), decode (checkpoint parts), skip
    (filtered scan planning), sql (device operators). Returns a
    logical digest that must be identical under ANY fault schedule."""
    dta.write_table(path, _batch(0, 2000), engine=eng)
    for b in range(1, 4):
        dta.write_table(path, _batch(b * 2000, 2000), engine=eng,
                        mode="append")
    Table.for_path(path, eng).checkpoint()
    for b in range(4, 6):
        dta.write_table(path, _batch(b * 2000, 2000), engine=eng,
                        mode="append")
    snap = Table.for_path(path, eng).latest_snapshot()
    filtered = dta.read_table(path, engine=eng,
                              filter=col("x") > lit(9_000))
    agg = sql(f"SELECT g, SUM(x) AS s, COUNT(*) AS c FROM '{path}' "
              f"GROUP BY g ORDER BY g", engine=eng)
    ordered = sql(f"SELECT x FROM '{path}' WHERE x < 100 "
                  f"ORDER BY x DESC LIMIT 7", engine=eng)
    full = dta.read_table(path, engine=eng)
    return (snap.version,
            sorted(filtered.column("x").to_pylist()),
            agg.to_pydict(),
            ordered.to_pydict(),
            sorted(full.column("x").to_pylist()))


_SOAK_RATES = dict(dispatch_error_rate=0.15, oom_rate=0.08,
                   stall_rate=0.08, recompile_rate=0.08)


def test_device_chaos_soak_converges_bit_identical():
    """THE acceptance property: under sustained seeded device chaos on
    every route, the workload's results are bit-identical to the
    fault-free run's — and the strict ledger audit stays green."""
    obs.set_hbm_obs_mode("strict")
    # both engines stay referenced through the audit: dropping an
    # engine mid-test would (correctly) record its still-resident
    # artifacts as leaks and fail the strict audit
    clean_eng, eng = _engine(), _engine()
    clean = _workload(clean_eng, "memory://dchaos-clean/tbl")
    ch = _chaos(11, **_SOAK_RATES)
    with ch:
        faulty = _workload(eng, "memory://dchaos-11/tbl")
    assert faulty == clean
    assert ch.total_faults > 0
    # chaos actually reached the gated routes, not just a corner
    gates_hit = {g for _k, _n, g in ch.fault_log if g}
    assert len(gates_hit) >= 3, gates_hit
    # strict audit: zero drift, zero leaks on every failure path
    assert hbm.audit()["ok"]
    assert not hbm.leak_records()


def test_device_chaos_soak_fault_schedule_replays(monkeypatch):
    """Same seed, same workload -> the identical fault schedule AND
    identical results: incidents replay from one integer. The pipelined
    log load dispatches from reader/parser threads (which interleaves
    fault *attribution* across runs), so this pins the serial path — the
    draw schedule itself is thread-safe by construction (one RNG under
    one lock) and the all-threads soaks above assert convergence."""
    monkeypatch.setenv("DELTA_TPU_PIPELINE", "off")
    ch_a = _chaos(23, **_SOAK_RATES)
    with ch_a:
        digest_a = _workload(_engine(), "memory://dchaos-a/tbl")
    # the replay must start from the state run A started from: empty
    # route breakers, empty resident ledger (a shed during run B must
    # not find run A's leftovers), fresh dispatch obs
    import gc
    gc.collect()
    resilience.reset()
    obs.reset_device_obs()
    obs.reset_hbm_obs()
    obs.set_device_obs_mode("on")
    ch_b = _chaos(23, **_SOAK_RATES)
    with ch_b:
        digest_b = _workload(_engine(), "memory://dchaos-b/tbl")
    assert ch_a.fault_log == ch_b.fault_log
    assert ch_a.fault_counts == ch_b.fault_counts
    assert digest_a == digest_b


def test_device_chaos_every_kind_absorbed():
    """Each fault kind alone converges — no kind relies on another's
    side effects to stay correct."""
    clean = _workload(_engine(), "memory://dchaos-kinds-clean/tbl")
    for i, rates in enumerate((
            dict(dispatch_error_rate=0.3),
            dict(oom_rate=0.3),
            dict(stall_rate=0.3),
            dict(recompile_rate=0.3))):
        resilience.reset()
        ch = _chaos(31 + i, **rates)
        with ch:
            digest = _workload(_engine(),
                               f"memory://dchaos-kind-{i}/tbl")
        assert digest == clean, f"diverged under {rates}"
        assert ch.total_faults > 0, f"nothing injected for {rates}"


def test_soak_breakers_trip_and_recover_on_schedule(monkeypatch):
    """Poison only the sql route at 100% and watch the breaker arc:
    trip within K classified failures, degrade decisions to the host
    twin, then re-arm through a half-open probe once chaos clears."""
    monkeypatch.setenv("DELTA_TPU_ROUTE_BREAKER_THRESHOLD", "2")
    monkeypatch.setenv("DELTA_TPU_ROUTE_BREAKER_RESET_S", "0.05")
    resilience.reset()
    eng = _engine()
    path = "memory://dchaos-breaker/tbl"
    dta.write_table(path, _batch(0, 4000), engine=eng)
    q = (f"SELECT g, SUM(x) AS s FROM '{path}' GROUP BY g ORDER BY g")
    want = sql(q, engine=eng).to_pydict()

    fallbacks = obs.counter("sql.device_fallbacks").value
    degrades = obs.counter("gate.route_breaker_degrades").value
    ch = _chaos(41, dispatch_error_rate=1.0)
    ch.kernel_filter = lambda name: name.startswith("sqlops.")
    with ch:
        for _ in range(4):
            assert sql(q, engine=eng).to_pydict() == want
        assert route_breaker_for("sql").state == "open"
        # every poisoned device attempt fell back and was counted
        assert obs.counter("sql.device_fallbacks").value > fallbacks
        # later queries were degraded at DECISION time (no device try)
        assert obs.counter(
            "gate.route_breaker_degrades").value > degrades
    # chaos gone: after the cooldown one probe re-arms the route
    time.sleep(0.06)
    assert sql(q, engine=eng).to_pydict() == want
    assert route_breaker_for("sql").state == "closed"
    reasons = [r["reason"] for r in obs.get_gate_records()
               if r["gate"] == "sql"]
    assert "breaker-open" in reasons and "breaker-probe" in reasons


def test_serve_stays_correct_under_device_chaos():
    """The serve workload: a live server answers correctly while the
    device plane is under chaos, and /health exposes the route
    breakers alongside the storage ones."""
    from delta_tpu.connect import connect
    from delta_tpu.serve import DeltaServeServer, ServeConfig

    eng = _engine()
    path = "memory://dchaos-serve/tbl"
    dta.write_table(path, _batch(0, 3000), engine=eng)
    srv = DeltaServeServer(
        "127.0.0.1", 0, engine=eng,
        config=ServeConfig.from_env(workers=2, max_queue=8,
                                    drain_grace_s=5.0))
    srv.start_background()
    try:
        host, port = srv.address
        with connect(host, port) as c:
            baseline = c.read_table(path).num_rows
            assert baseline == 3000
            with _chaos(53, **_SOAK_RATES) as ch:
                for _ in range(3):
                    assert c.read_table(path).num_rows == baseline
            h = c.health()
            assert "breakers" in h
    finally:
        srv.shutdown(1.0)
    assert not hbm.leak_records()


@pytest.mark.slow
def test_device_chaos_soak_many_seeds_thousand_faults():
    """The long soak: accumulate >=1000 injected faults across seeds;
    every run must converge bit-identically with a green strict audit
    and zero ledger leaks. Fixed seeds — failures replay exactly."""
    obs.set_hbm_obs_mode("strict")
    clean_eng = _engine()
    clean = _workload(clean_eng, "memory://dchaos-slow-clean/tbl")
    rates = dict(dispatch_error_rate=0.25, oom_rate=0.15,
                 stall_rate=0.15, recompile_rate=0.15)
    total = 0
    seed = 100
    while total < 1000:
        resilience.reset()
        # sweep the previous seed's residents (its engine is about to
        # be dropped) so each run audits only its own artifacts
        obs.reset_hbm_obs()
        eng = _engine()
        ch = _chaos(seed, **rates)
        with ch:
            digest = _workload(eng, f"memory://dchaos-slow-{seed}/tbl")
        assert digest == clean, f"seed {seed} diverged"
        assert hbm.audit()["ok"], f"seed {seed} failed the audit"
        assert not hbm.leak_records(), f"seed {seed} leaked"
        total += ch.total_faults
        seed += 1
        assert seed < 200, "fault rates too low to reach 1000 faults"
    assert total >= 1000

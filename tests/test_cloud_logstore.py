"""Cloud LogStore semantics: GCS conditional put over real HTTP, S3
single-driver, and the external-arbiter protocol with half-commit
recovery under injected faults at every phase boundary."""

import json
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import pyarrow as pa
import pytest

import delta_tpu.api as dta
from delta_tpu.engine.host import HostEngine
from delta_tpu.storage.cloud import (
    ExternalArbiterLogStore,
    ExternalCommitEntry,
    GCSLogStore,
    GCSObjectClient,
    HttpTransport,
    InMemoryCommitArbiter,
    S3SingleDriverLogStore,
)
from delta_tpu.storage.logstore import (
    DelegatingLogStore,
    FileAlreadyExistsError,
    InMemoryLogStore,
)
from delta_tpu.table import Table


# ------------------------------------------------------- mock GCS server


class _GCSState:
    def __init__(self):
        self.lock = threading.Lock()
        self.objects = {}  # name -> (bytes, generation)
        self.next_gen = 1


class _GCSHandler(BaseHTTPRequestHandler):
    state: _GCSState = None  # set by the fixture

    def log_message(self, *a):  # silence
        pass

    def _send(self, status, body=b"", ctype="application/json"):
        self.send_response(status)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_POST(self):
        parsed = urllib.parse.urlparse(self.path)
        q = dict(urllib.parse.parse_qsl(parsed.query))
        if not parsed.path.startswith("/upload/storage/v1/b/"):
            return self._send(404)
        name = q["name"]
        length = int(self.headers.get("Content-Length", 0))
        data = self.rfile.read(length)
        st = self.state
        with st.lock:
            existing = st.objects.get(name)
            cond = q.get("ifGenerationMatch")
            if cond is not None:
                want = int(cond)
                have = existing[1] if existing else 0
                if want != have:
                    return self._send(412, b'{"error":"precondition"}')
            st.objects[name] = (data, st.next_gen)
            st.next_gen += 1
        self._send(200, json.dumps({"name": name}).encode())

    def do_GET(self):
        parsed = urllib.parse.urlparse(self.path)
        q = dict(urllib.parse.parse_qsl(parsed.query))
        st = self.state
        prefix_list = "/storage/v1/b/"
        if not parsed.path.startswith(prefix_list):
            return self._send(404)
        rest = parsed.path[len(prefix_list):]
        _bucket, _, obj_part = rest.partition("/o")
        if obj_part in ("", "/") and "alt" not in q:  # listing
            with st.lock:
                items = [
                    {"name": n, "size": str(len(d)),
                     "updated": "2026-01-01T00:00:00Z"}
                    for n, (d, _g) in sorted(st.objects.items())
                    if n.startswith(q.get("prefix", ""))
                ]
            return self._send(200, json.dumps({"items": items}).encode())
        name = urllib.parse.unquote(obj_part.lstrip("/"))
        with st.lock:
            entry = st.objects.get(name)
        if entry is None:
            return self._send(404)
        if q.get("alt") != "media":  # metadata GET
            meta = {"name": name, "size": str(len(entry[0])),
                    "generation": str(entry[1]),
                    "updated": "2026-01-01T00:00:00Z"}
            return self._send(200, json.dumps(meta).encode())
        self._send(200, entry[0], "application/octet-stream")

    def do_DELETE(self):
        parsed = urllib.parse.urlparse(self.path)
        name = urllib.parse.unquote(parsed.path.rpartition("/o/")[2])
        st = self.state
        with st.lock:
            if name not in st.objects:
                return self._send(404)
            del st.objects[name]
        self._send(204)


@pytest.fixture
def gcs_server():
    state = _GCSState()
    handler = type("H", (_GCSHandler,), {"state": state})
    server = ThreadingHTTPServer(("127.0.0.1", 0), handler)
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    try:
        yield f"http://127.0.0.1:{server.server_address[1]}", state
    finally:
        server.shutdown()


def _gcs_store(base_url):
    client = GCSObjectClient("bkt", transport=HttpTransport(),
                             base_url=base_url)
    return GCSLogStore(client)


# ----------------------------------------------------------- GCS tests


def test_gcs_put_if_absent_over_http(gcs_server):
    base, _ = gcs_server
    store = _gcs_store(base)
    store.write("gs://bkt/t/_delta_log/00000000000000000000.json", b"a")
    with pytest.raises(FileAlreadyExistsError):
        store.write("gs://bkt/t/_delta_log/00000000000000000000.json", b"b")
    assert store.read("gs://bkt/t/_delta_log/00000000000000000000.json") == b"a"
    store.write("gs://bkt/t/_delta_log/00000000000000000000.json", b"c",
                overwrite=True)
    assert store.read("gs://bkt/t/_delta_log/00000000000000000000.json") == b"c"


def test_gcs_list_from_and_walk(gcs_server):
    base, _ = gcs_server
    store = _gcs_store(base)
    for v in range(3):
        store.write(f"gs://bkt/t/_delta_log/{v:020d}.json", b"x")
    store.write("gs://bkt/t/_delta_log/_sidecars/a.parquet", b"y")
    listed = list(store.list_from(f"gs://bkt/t/_delta_log/{1:020d}.json"))
    names = [p.path.rpartition("/")[2] for p in listed]
    assert names == [f"{1:020d}.json", f"{2:020d}.json"]  # no subdir files
    walked = [p.path for p in store.walk("gs://bkt/t/_delta_log")]
    assert len(walked) == 4
    assert store.exists("gs://bkt/t/_delta_log/00000000000000000002.json")
    store.delete("gs://bkt/t/_delta_log/00000000000000000002.json")
    assert not store.exists("gs://bkt/t/_delta_log/00000000000000000002.json")


def test_gcs_end_to_end_table(gcs_server):
    """A real table write/DML/read cycle against the GCS store through
    the engine SPI — the full product path over HTTP."""
    base, _ = gcs_server
    store = _gcs_store(base)

    def resolver(path):
        return store

    eng = HostEngine(store_resolver=resolver)
    path = "gs://bkt/tables/t1"
    data = pa.table({"id": pa.array(np.arange(10, dtype=np.int64))})
    dta.write_table(path, data, engine=eng)
    dta.write_table(path, data, mode="append", engine=eng)
    out = dta.read_table(path, engine=eng)
    assert out.num_rows == 20
    snap = Table.for_path(path, eng).latest_snapshot()
    assert snap.version == 1 and snap.num_files == 2


# ------------------------------------------------------------ S3 tests


def test_s3_single_driver_put_if_absent():
    inner = InMemoryLogStore()
    store = S3SingleDriverLogStore(inner)
    store.write("s3://b/t/_delta_log/x.json", b"1")
    with pytest.raises(FileAlreadyExistsError):
        store.write("s3://b/t/_delta_log/x.json", b"2")
    assert store.read("s3://b/t/_delta_log/x.json") == b"1"


# ----------------------------------------------- external arbiter tests


class RacyS3Store(DelegatingLogStore):
    """Models S3's lack of conditional put: overwrite=False is a
    non-atomic check-then-put."""

    def write(self, path, data, overwrite=False):
        if not overwrite and self.inner.exists(path):
            raise FileAlreadyExistsError(path)
        self.inner.write(path, data, overwrite=True)

    def is_partial_write_visible(self, path):
        return False


def _arbiter_store():
    return ExternalArbiterLogStore(RacyS3Store(InMemoryLogStore()),
                                   InMemoryCommitArbiter())


TBL = "s3://bkt/tbl"
LOG = TBL + "/_delta_log"


def _commit(store, v, data=b"{}"):
    store.write(f"{LOG}/{v:020d}.json", data)


def test_arbiter_normal_commits_and_conflict():
    store = _arbiter_store()
    _commit(store, 0)
    _commit(store, 1)
    with pytest.raises(FileAlreadyExistsError):
        _commit(store, 1)
    names = [f.path.rpartition("/")[2]
             for f in store.list_from(f"{LOG}/{0:020d}.json")]
    assert [n for n in names if n.endswith(".json")] == \
        [f"{0:020d}.json", f"{1:020d}.json"]
    entry = store.arbiter.get_entry(TBL, f"{1:020d}.json")
    assert entry.complete and entry.expire_time is not None


def test_arbiter_missing_previous_commit_rejected():
    store = _arbiter_store()
    _commit(store, 0)
    with pytest.raises(FileNotFoundError):
        _commit(store, 5)


def _crash(exc=RuntimeError("injected crash")):
    def boom(*a, **k):
        raise exc
    return boom


def test_recovery_after_crash_before_copy():
    """Writer dies between PREPARE (arbiter entry) and COMMIT (copy):
    N.json is missing but the entry exists incomplete. The next reader's
    listFrom completes the commit from the temp file."""
    store = _arbiter_store()
    _commit(store, 0)
    store._write_copy_temp_file = _crash()
    _commit(store, 1, b'{"add":1}')  # returns: crash window swallowed
    assert not store.inner.exists(f"{LOG}/{1:020d}.json")
    entry = store.arbiter.get_entry(TBL, f"{1:020d}.json")
    assert entry is not None and not entry.complete

    reader = _arbiter_store().__class__(store.inner, store.arbiter)
    names = [f.path.rpartition("/")[2]
             for f in reader.list_from(f"{LOG}/{0:020d}.json")]
    assert f"{1:020d}.json" in names
    assert reader.read(f"{LOG}/{1:020d}.json") == b'{"add":1}'
    assert store.arbiter.get_entry(TBL, f"{1:020d}.json").complete


def test_recovery_after_crash_before_ack():
    """Writer dies between COMMIT (copy done) and ACKNOWLEDGE: N.json
    exists, entry incomplete. Recovery must only mark complete, not
    re-copy (the copy raises FileAlreadyExists and is tolerated)."""
    store = _arbiter_store()
    _commit(store, 0)
    store._write_put_complete_entry = _crash()
    _commit(store, 1, b'{"add":2}')
    assert store.inner.exists(f"{LOG}/{1:020d}.json")
    assert not store.arbiter.get_entry(TBL, f"{1:020d}.json").complete

    reader = ExternalArbiterLogStore(store.inner, store.arbiter)
    list(reader.list_from(f"{LOG}/{0:020d}.json"))
    assert store.arbiter.get_entry(TBL, f"{1:020d}.json").complete


def test_next_writer_repairs_half_commit():
    """Writing N+1 first repairs a half-committed N (write algorithm
    step 1), so the log never gains holes."""
    store = _arbiter_store()
    _commit(store, 0)
    store._write_copy_temp_file = _crash()
    _commit(store, 1, b'{"v":1}')
    del store._write_copy_temp_file  # restore class impl

    _commit(store, 2, b'{"v":2}')
    assert store.read(f"{LOG}/{1:020d}.json") == b'{"v":1}'
    assert store.read(f"{LOG}/{2:020d}.json") == b'{"v":2}'
    assert store.arbiter.get_entry(TBL, f"{1:020d}.json").complete


def test_arbiter_wins_race_on_racy_store():
    """Two writers race version 1 over a store with NO conditional put:
    exactly one arbiter entry wins; the loser surfaces a commit
    conflict even though the underlying store would have let both
    writes through."""
    store = _arbiter_store()
    _commit(store, 0)
    outcome = []
    barrier = threading.Barrier(2)

    def writer(tag):
        w = ExternalArbiterLogStore(store.inner, store.arbiter)
        barrier.wait()
        try:
            w.write(f"{LOG}/{1:020d}.json", b"w" + tag)
            outcome.append(("ok", tag))
        except FileAlreadyExistsError:
            outcome.append(("conflict", tag))

    ts = [threading.Thread(target=writer, args=(t,)) for t in (b"A", b"B")]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert sorted(o for o, _ in outcome) == ["conflict", "ok"]
    winner_tag = next(t for o, t in outcome if o == "ok")
    assert store.read(f"{LOG}/{1:020d}.json") == b"w" + winner_tag


def test_transaction_level_recovery_through_engine():
    """End-to-end: a writer's commit crashes mid-protocol; a fresh
    reader of the TABLE (not the store) still sees the committed data
    because listFrom repairs the log before listing."""
    inner = RacyS3Store(InMemoryLogStore())
    arbiter = InMemoryCommitArbiter()

    def resolver(path):
        return ExternalArbiterLogStore(inner, arbiter)

    eng = HostEngine(store_resolver=resolver)
    path = "s3://bkt/tbl"
    data = pa.table({"x": pa.array(np.arange(5, dtype=np.int64))})
    dta.write_table(path, data, engine=eng)

    crashing = ExternalArbiterLogStore(inner, arbiter)
    crashing._write_copy_temp_file = _crash()

    def crash_resolver(p):
        return crashing

    eng_crash = HostEngine(store_resolver=crash_resolver)
    dta.write_table(path, data, mode="append", engine=eng_crash)
    # version 1 exists only as temp file + incomplete arbiter entry

    eng2 = HostEngine(store_resolver=resolver)
    snap = Table.for_path(path, eng2).latest_snapshot()
    assert snap.version == 1
    assert dta.read_table(path, engine=eng2).num_rows == 10
    assert arbiter.get_entry(path, f"{1:020d}.json").complete

"""Symlink-format manifest generation: full GENERATE, incremental hook,
and the DV / column-mapping gates."""

import os

import numpy as np
import pyarrow as pa
import pytest

import delta_tpu.api as dta
from delta_tpu.commands.dml import delete
from delta_tpu.commands.generate import (
    MANIFEST_DIR,
    MANIFEST_NAME,
    generate_symlink_manifest,
)
from delta_tpu.errors import DeltaError
from delta_tpu.expressions.parser import parse_expression
from delta_tpu.sql import sql
from delta_tpu.table import Table


def _read_manifest(path):
    with open(path) as f:
        return [l for l in f.read().splitlines() if l]


def test_generate_unpartitioned(tmp_table_path):
    dta.write_table(tmp_table_path,
                    pa.table({"id": pa.array([1, 2, 3], pa.int64())}),
                    mode="append")
    written = generate_symlink_manifest(Table.for_path(tmp_table_path))
    loc = f"{tmp_table_path}/{MANIFEST_DIR}/{MANIFEST_NAME}"
    assert list(written) == [loc]
    lines = _read_manifest(loc)
    live = {os.path.join(tmp_table_path, f.path)
            for f in Table.for_path(tmp_table_path).latest_snapshot().scan().files()}
    assert set(lines) == live
    assert all(os.path.isfile(l) for l in lines)


def test_generate_partitioned_and_stale_cleanup(tmp_table_path):
    data = pa.table({
        "id": pa.array(np.arange(20, dtype=np.int64)),
        "part": pa.array(["a"] * 10 + ["b"] * 10),
    })
    dta.write_table(tmp_table_path, data, mode="append", partition_by=["part"])
    written = generate_symlink_manifest(Table.for_path(tmp_table_path))
    assert len(written) == 2
    assert any("part=a" in p for p in written)
    assert any("part=b" in p for p in written)
    # the files actually exist on disk at the reported locations and
    # name real data files
    for loc, n in written.items():
        assert os.path.isfile(loc), loc
        lines = _read_manifest(loc)
        assert len(lines) == n
        assert all(os.path.isfile(l) for l in lines)

    # delete all of partition b, regenerate → its manifest disappears
    delete(Table.for_path(tmp_table_path), parse_expression("part = 'b'"))
    written = generate_symlink_manifest(Table.for_path(tmp_table_path))
    assert len(written) == 1
    assert not os.path.exists(
        f"{tmp_table_path}/{MANIFEST_DIR}/part=b/{MANIFEST_NAME}")


def test_incremental_hook_on_commit(tmp_table_path):
    dta.write_table(
        tmp_table_path,
        pa.table({"id": pa.array([1], pa.int64()),
                  "part": pa.array(["a"])}),
        mode="append", partition_by=["part"],
        properties={"delta.compatibility.symlinkFormatManifest.enabled": "true"})
    loc_a = f"{tmp_table_path}/{MANIFEST_DIR}/part=a/{MANIFEST_NAME}"
    assert os.path.isfile(loc_a), "hook should fire on the creating commit"

    # append to a new partition: only that partition's manifest appears
    dta.write_table(
        tmp_table_path,
        pa.table({"id": pa.array([2], pa.int64()), "part": pa.array(["b"])}),
        mode="append")
    loc_b = f"{tmp_table_path}/{MANIFEST_DIR}/part=b/{MANIFEST_NAME}"
    assert os.path.isfile(loc_b)
    assert len(_read_manifest(loc_b)) == 1

    # delete partition a: manifest removed by the hook
    delete(Table.for_path(tmp_table_path), parse_expression("part = 'a'"))
    assert not os.path.exists(loc_a)
    assert os.path.isfile(loc_b)


def test_generate_refuses_dvs(tmp_table_path):
    dta.write_table(tmp_table_path,
                    pa.table({"id": pa.array(np.arange(100, dtype=np.int64))}),
                    mode="append",
                    properties={"delta.enableDeletionVectors": "true"})
    delete(Table.for_path(tmp_table_path), parse_expression("id < 5"))
    with pytest.raises(DeltaError, match="deletion vectors"):
        generate_symlink_manifest(Table.for_path(tmp_table_path))


def test_generate_refuses_column_mapping(tmp_table_path):
    dta.write_table(tmp_table_path,
                    pa.table({"id": pa.array([1], pa.int64())}),
                    mode="append",
                    properties={"delta.columnMapping.mode": "name"})
    with pytest.raises(DeltaError, match="column-mapped"):
        generate_symlink_manifest(Table.for_path(tmp_table_path))


def test_manifest_hook_failure_surfaces(tmp_table_path):
    """A DV write on a manifest-enabled table must raise (commit lands,
    but the stale manifest is a correctness hazard for external
    engines)."""
    from delta_tpu.hooks import PostCommitHookError

    dta.write_table(
        tmp_table_path,
        pa.table({"id": pa.array(np.arange(10, dtype=np.int64))}),
        mode="append",
        properties={
            "delta.compatibility.symlinkFormatManifest.enabled": "true",
            "delta.enableDeletionVectors": "true",
        })
    with pytest.raises(PostCommitHookError, match="deletion vectors"):
        delete(Table.for_path(tmp_table_path), parse_expression("id < 5"))
    # the delete itself committed
    assert Table.for_path(tmp_table_path).latest_snapshot().version == 1
    assert dta.read_table(tmp_table_path).num_rows == 5


def test_sql_path_guard():
    from delta_tpu.errors import DeltaError as DE

    def guard(path):
        raise DE(f"blocked: {path}")

    with pytest.raises(DE, match="blocked"):
        sql("SELECT * FROM '/anywhere/at/all'", path_guard=guard)


def test_sql_generate(tmp_table_path):
    dta.write_table(tmp_table_path,
                    pa.table({"id": pa.array([1], pa.int64())}),
                    mode="append")
    written = sql(f"GENERATE symlink_format_manifest FOR TABLE '{tmp_table_path}'")
    assert len(written) == 1

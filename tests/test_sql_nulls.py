"""SQL null-semantics regressions (round-3 advisor findings).

The reference's engine (Spark SQL) implements full three-valued logic
and null-rejecting join keys; these tests pin the same behavior in
`delta_tpu.sqlengine` — null join keys never match, NULL propagates
through NOT/IN/BETWEEN/LIKE/<> and collapses to False only at filter
boundaries.
"""

import numpy as np
import pyarrow as pa
import pytest

import delta_tpu.api as dta
from delta_tpu.errors import DeltaError
from delta_tpu.sql import sql


@pytest.fixture
def nullkeys(tmp_path):
    """Two tables whose join columns contain nulls; arrow nullable
    int64 becomes float64+NaN in pandas, the exact shape that made
    pandas merge match NULL==NULL."""
    a = str(tmp_path / "a")
    b = str(tmp_path / "b")
    dta.write_table(a, pa.table({
        "k": pa.array([1, 2, None, None], pa.int64()),
        "av": pa.array([10, 20, 30, 40], pa.int64()),
    }))
    dta.write_table(b, pa.table({
        "k2": pa.array([2, None, 3], pa.int64()),
        "bv": pa.array([200, 300, 400], pa.int64()),
    }))
    return a, b


def test_inner_join_null_keys_never_match(nullkeys):
    a, b = nullkeys
    out = sql(f"SELECT a.av, b.bv FROM '{a}' a JOIN '{b}' b "
              f"ON a.k = b.k2")
    assert out.column("av").to_pylist() == [20]
    assert out.column("bv").to_pylist() == [200]


def test_implicit_join_null_keys_never_match(nullkeys):
    a, b = nullkeys
    out = sql(f"SELECT a.av, b.bv FROM '{a}' a, '{b}' b "
              f"WHERE a.k = b.k2")
    assert out.column("av").to_pylist() == [20]


def test_left_join_null_keys_null_extended(nullkeys):
    a, b = nullkeys
    out = sql(f"SELECT a.av, b.bv FROM '{a}' a LEFT JOIN '{b}' b "
              f"ON a.k = b.k2 ORDER BY av")
    assert out.column("av").to_pylist() == [10, 20, 30, 40]
    # null-key left rows survive but never match the null-key right row
    assert out.column("bv").to_pylist() == [None, 200, None, None]


def test_full_outer_join_null_keys_both_sides(nullkeys):
    a, b = nullkeys
    out = sql(f"SELECT a.av, b.bv FROM '{a}' a FULL OUTER JOIN '{b}' b "
              f"ON a.k = b.k2")
    # 4 left rows (one matched) + 2 unmatched right rows (null-key b
    # and k2=3) = 6
    assert out.num_rows == 6
    pairs = set(zip(out.column("av").to_pylist(),
                    out.column("bv").to_pylist()))
    assert (20, 200) in pairs
    assert (None, 300) in pairs and (None, 400) in pairs


def test_not_equals_excludes_nulls(tmp_table_path):
    # <> on a float column: NaN != x is True in numpy, NULL in SQL
    dta.write_table(tmp_table_path, pa.table({
        "v": pa.array([1, 2, None], pa.int64()),
    }))
    out = sql(f"SELECT v FROM '{tmp_table_path}' WHERE v <> 1")
    assert out.column("v").to_pylist() == [2]


def test_not_in_excludes_nulls(tmp_table_path):
    dta.write_table(tmp_table_path, pa.table({
        "v": pa.array([1, 2, None], pa.int64()),
    }))
    out = sql(f"SELECT v FROM '{tmp_table_path}' WHERE v NOT IN (1)")
    assert out.column("v").to_pylist() == [2]


def test_not_between_excludes_nulls(tmp_table_path):
    dta.write_table(tmp_table_path, pa.table({
        "v": pa.array([1, 5, None], pa.int64()),
    }))
    out = sql(f"SELECT v FROM '{tmp_table_path}' "
              f"WHERE v NOT BETWEEN 0 AND 2")
    assert out.column("v").to_pylist() == [5]


def test_not_like_excludes_nulls(tmp_table_path):
    dta.write_table(tmp_table_path, pa.table({
        "s": pa.array(["apple", "banana", None]),
    }))
    out = sql(f"SELECT s FROM '{tmp_table_path}' "
              f"WHERE s NOT LIKE 'a%'")
    assert out.column("s").to_pylist() == ["banana"]


def test_not_predicate_excludes_nulls(tmp_table_path):
    dta.write_table(tmp_table_path, pa.table({
        "v": pa.array([1, 3, None], pa.int64()),
    }))
    out = sql(f"SELECT v FROM '{tmp_table_path}' WHERE NOT (v < 2)")
    assert out.column("v").to_pylist() == [3]


def test_kleene_or_null_recovers(tmp_table_path):
    # NULL OR TRUE must be TRUE; NULL OR FALSE is NULL -> excluded
    dta.write_table(tmp_table_path, pa.table({
        "a": pa.array([None, None], pa.int64()),
        "b": pa.array([7, 0], pa.int64()),
    }))
    out = sql(f"SELECT b FROM '{tmp_table_path}' "
              f"WHERE a > 0 OR b = 7")
    assert out.column("b").to_pylist() == [7]


def test_not_and_with_null_kleene(tmp_table_path):
    # NOT(a > 0 AND b = 7): row (NULL, 0) -> NOT(NULL AND FALSE) ->
    # NOT(FALSE) -> TRUE; early collapse would also pass, but row
    # (NULL, 7) -> NOT(NULL) -> NULL -> excluded
    dta.write_table(tmp_table_path, pa.table({
        "a": pa.array([None, None, 1], pa.int64()),
        "b": pa.array([0, 7, 7], pa.int64()),
    }))
    out = sql(f"SELECT a, b FROM '{tmp_table_path}' "
              f"WHERE NOT (a > 0 AND b = 7) ORDER BY b")
    assert out.column("b").to_pylist() == [0]


def test_not_in_subquery_with_null_matches_nothing(tmp_path):
    # famous SQL footgun: NOT IN (subquery containing NULL) is never
    # TRUE for any non-matching row
    a = str(tmp_path / "a")
    b = str(tmp_path / "b")
    dta.write_table(a, pa.table({"v": pa.array([1, 9], pa.int64())}))
    dta.write_table(b, pa.table({"w": pa.array([1, None], pa.int64())}))
    out = sql(f"SELECT v FROM '{a}' WHERE v NOT IN "
              f"(SELECT w FROM '{b}')")
    assert out.num_rows == 0
    # without the NULL the non-match comes back
    c = str(tmp_path / "c")
    dta.write_table(c, pa.table({"w": pa.array([1], pa.int64())}))
    out = sql(f"SELECT v FROM '{a}' WHERE v NOT IN "
              f"(SELECT w FROM '{c}')")
    assert out.column("v").to_pylist() == [9]


def test_timestamp_as_of_iso_string_select(tmp_table_path):
    import datetime
    import time

    dta.write_table(tmp_table_path, pa.table(
        {"v": pa.array([1], pa.int64())}))
    time.sleep(0.05)
    mid = datetime.datetime.now().isoformat()
    time.sleep(0.05)
    dta.write_table(tmp_table_path, pa.table(
        {"v": pa.array([2], pa.int64())}), mode="append")
    # ISO string between the two commits resolves to version 0; the
    # bug was an uncaught ValueError from int('<iso>')
    out = sql(f"SELECT v FROM '{tmp_table_path}' "
              f"TIMESTAMP AS OF '{mid}' ORDER BY v")
    assert out.column("v").to_pylist() == [1]


def test_having_without_group_by_with_aggregate(tmp_table_path):
    dta.write_table(tmp_table_path, pa.table(
        {"v": pa.array([1, 2, 3], pa.int64())}))
    out = sql(f"SELECT SUM(v) AS total FROM '{tmp_table_path}' "
              f"HAVING SUM(v) > 5")
    assert out.column("total").to_pylist() == [6]
    out = sql(f"SELECT SUM(v) AS total FROM '{tmp_table_path}' "
              f"HAVING SUM(v) > 100")
    assert out.num_rows == 0
    # still rejected with no aggregate anywhere
    with pytest.raises(DeltaError, match="HAVING"):
        sql(f"SELECT v FROM '{tmp_table_path}' HAVING v > 1")


def test_arbiter_synchronous_full():
    # acked conditional puts must be power-loss durable (advisor low)
    import sqlite3
    import tempfile

    from delta_tpu.storage.arbiter import SqliteCommitArbiter

    with tempfile.TemporaryDirectory() as d:
        arb = SqliteCommitArbiter(d + "/arb.db")
        conn = arb._connect()
        assert conn.execute("PRAGMA synchronous").fetchone()[0] == 2
        conn.close()

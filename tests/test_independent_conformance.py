"""Three-way conformance: product engines vs the independent oracle vs
hand-written expected states, over checked-in golden fixtures produced
by an independent writer (tests/golden_fixtures/generate.py — stdlib +
pyarrow only, no delta_tpu code).

This is the mechanism a shared parser bug cannot survive: the fixtures'
`expected.json` digests were written by hand from the commit contents,
the oracle (tests/independent_oracle.py) reimplements replay from
PROTOCOL.md with no shared code, and both product engines must agree
with both. The reverse direction (oracle reads tables OUR writer
produced, including checkpoints and DV deletes) closes the loop.
"""

import json
import os

import numpy as np
import pyarrow as pa
import pytest

import delta_tpu.api as dta
from delta_tpu.engine.host import HostEngine
from delta_tpu.engine.tpu import TpuEngine
from delta_tpu.table import Table

from tests.independent_oracle import read_table_state

FIXTURES = os.path.join(os.path.dirname(__file__), "golden_fixtures")
FIXTURE_NAMES = sorted(
    d for d in os.listdir(FIXTURES)
    if os.path.isdir(os.path.join(FIXTURES, d)))


def engine_summary(path, engine):
    """The product's view of the table state, in the oracle's digest
    shape."""
    snap = Table.for_path(path, engine).latest_snapshot()
    tbl = snap.state.add_files_table
    paths = tbl.column("path").to_pylist()
    dvs = tbl.column("dv_id").to_pylist()
    tombs = snap.state.tombstones_table
    t_paths = tombs.column("path").to_pylist()
    t_dvs = tombs.column("dv_id").to_pylist()
    proto = snap.protocol
    out = {
        "live_keys": sorted(f"{p}|{dv or ''}" for p, dv in zip(paths, dvs)),
        "tombstone_keys": sorted(
            f"{p}|{dv or ''}" for p, dv in zip(t_paths, t_dvs)),
        "num_live": snap.num_files,
        "live_bytes": snap.state.size_in_bytes,
        "protocol": {k: v for k, v in {
            "minReaderVersion": proto.minReaderVersion,
            "minWriterVersion": proto.minWriterVersion,
            "readerFeatures": proto.readerFeatures,
            "writerFeatures": proto.writerFeatures,
        }.items() if v is not None},
        "metadata_id": snap.metadata.id,
        "configuration": dict(snap.metadata.configuration),
        "txns": {k: t.version
                 for k, t in snap.state.set_transactions.items()},
        "version": snap.version,
    }
    return out


def _check(expected: dict, actual: dict, who: str):
    for k, v in expected.items():
        if k == "latest_ict":
            continue  # engine surface checked separately below
        assert k in actual, f"{who} digest lacks {k}"
        assert actual[k] == v, (
            f"{who} disagrees on {k}: {actual[k]!r} != expected {v!r}")


@pytest.mark.parametrize("name", FIXTURE_NAMES)
def test_fixture_three_way(name):
    root = os.path.join(FIXTURES, name)
    with open(os.path.join(root, "expected.json")) as f:
        expected = json.load(f)

    oracle = read_table_state(root).summary()
    oracle["version"] = expected["version"]  # oracle has no version field
    _check(expected, oracle, "oracle")
    if "latest_ict" in expected:
        assert oracle["latest_ict"] == expected["latest_ict"]

    for engine_cls in (HostEngine, TpuEngine):
        got = engine_summary(root, engine_cls())
        _check(expected, got, engine_cls.__name__)

    if "latest_ict" in expected:
        # ICT surfaces through the engines' history/timestamp path
        snap = Table.for_path(root, HostEngine()).latest_snapshot()
        ci = snap.state.latest_commit_info
        assert ci is not None and ci.inCommitTimestamp == expected["latest_ict"]


def test_oracle_reads_our_writer(tmp_path):
    """Reverse direction: a table produced by OUR writer (appends,
    delete, checkpoint) must reconstruct identically under the
    independent oracle."""
    p = str(tmp_path / "tbl")
    dta.write_table(p, pa.table(
        {"id": pa.array(np.arange(500, dtype=np.int64))}),
        target_rows_per_file=100)
    for i in range(4):
        dta.write_table(p, pa.table(
            {"id": pa.array(np.arange(i * 50, i * 50 + 50,
                                      dtype=np.int64))}),
            mode="append")
    from delta_tpu.commands.dml import delete
    from delta_tpu.expressions import col, lit

    delete(Table.for_path(p), predicate=col("id") >= lit(480))
    table = Table.for_path(p)
    table.checkpoint()
    dta.write_table(p, pa.table(
        {"id": pa.array(np.arange(7, dtype=np.int64))}), mode="append")

    oracle = read_table_state(p).summary()
    for engine_cls in (HostEngine, TpuEngine):
        got = engine_summary(p, engine_cls())
        assert got["live_keys"] == oracle["live_keys"], engine_cls.__name__
        assert got["num_live"] == oracle["num_live"]
        assert got["live_bytes"] == oracle["live_bytes"]
        assert got["tombstone_keys"] == oracle["tombstone_keys"]
        assert got["txns"] == oracle["txns"]


def test_oracle_reads_our_dv_and_v2_checkpoint(tmp_path):
    """Our DV-writing DML + V2 checkpoint output, read back by the
    oracle."""
    p = str(tmp_path / "tbl")
    dta.write_table(p, pa.table(
        {"id": pa.array(np.arange(200, dtype=np.int64))}),
        target_rows_per_file=50,
        properties={"delta.enableDeletionVectors": "true"})
    from delta_tpu.commands.dml import delete
    from delta_tpu.expressions import col, lit

    delete(Table.for_path(p), predicate=(col("id") >= lit(30)) & (col("id") < lit(40)))
    oracle = read_table_state(p).summary()
    got = engine_summary(p, HostEngine())
    assert got["live_keys"] == oracle["live_keys"]
    assert any("|" in k and k.split("|", 1)[1] for k in oracle["live_keys"]), \
        "expected at least one live file carrying a DV id"

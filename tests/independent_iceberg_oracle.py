"""Independent Iceberg metadata reader — the UniForm conformance
oracle (VERDICT r3 ask #6).

Reconstructs a converted table's live data-file set purely from the
Iceberg spec: version-hint → vN.metadata.json → current snapshot →
manifest-list (Avro OCF) → manifests (Avro OCF) → data-file entries
with ADDED/EXISTING status. Shares ZERO code with
`delta_tpu.interop` — including Avro: the object-container-file
decoder below is written from the Avro 1.11 specification
(https://avro.apache.org/docs/1.11.1/specification/), the same way
`tests/independent_oracle.py` re-reads the Delta log from
PROTOCOL.md.

Reference counterpart: real Iceberg libraries reading UniForm output
(`IcebergConversionTransaction.scala:1` writes through the actual
Iceberg SDK; pyiceberg is not in this environment, so the spec itself
is the arbiter).
"""

from __future__ import annotations

import json
import os
import struct

# --------------------------------------------------- Avro (from spec)

_MAGIC = b"Obj\x01"


class _Reader:
    def __init__(self, data: bytes):
        self.data = data
        self.pos = 0

    def read(self, n: int) -> bytes:
        if self.pos + n > len(self.data):
            raise EOFError("truncated avro data")
        out = self.data[self.pos:self.pos + n]
        self.pos += n
        return out

    def at_end(self) -> bool:
        return self.pos >= len(self.data)

    # spec: ints/longs are zig-zag encoded variable-length integers
    def varint(self) -> int:
        shift = 0
        acc = 0
        while True:
            b = self.read(1)[0]
            acc |= (b & 0x7F) << shift
            if not b & 0x80:
                break
            shift += 7
        return (acc >> 1) ^ -(acc & 1)

    def bytes_(self) -> bytes:
        return self.read(self.varint())

    def string(self) -> str:
        return self.bytes_().decode("utf-8")


def _decode(r: _Reader, schema):
    """Decode one value of `schema` (the spec's per-type encodings for
    the subset Iceberg metadata uses)."""
    if isinstance(schema, str):
        t = schema
    elif isinstance(schema, list):  # union: varint branch index
        branch = r.varint()
        return _decode(r, schema[branch])
    elif isinstance(schema, dict):
        t = schema["type"]
    else:
        raise ValueError(f"bad schema node {schema!r}")

    if t == "null":
        return None
    if t == "boolean":
        return r.read(1) != b"\x00"
    if t in ("int", "long"):
        return r.varint()
    if t == "float":
        return struct.unpack("<f", r.read(4))[0]
    if t == "double":
        return struct.unpack("<d", r.read(8))[0]
    if t == "bytes":
        return r.bytes_()
    if t == "string":
        return r.string()
    if t == "record":
        return {f["name"]: _decode(r, f["type"])
                for f in schema["fields"]}
    if t == "array":
        out = []
        while True:
            n = r.varint()
            if n == 0:
                break
            if n < 0:  # negative count: block byte size follows
                r.varint()
                n = -n
            for _ in range(n):
                out.append(_decode(r, schema["items"]))
        return out
    if t == "map":
        out = {}
        while True:
            n = r.varint()
            if n == 0:
                break
            if n < 0:
                r.varint()
                n = -n
            for _ in range(n):
                out[r.string()] = _decode(r, schema["values"])
        return out
    if t == "fixed":
        return r.read(schema["size"])
    raise ValueError(f"unsupported avro type {t!r}")


def read_avro_file(path: str):
    """Spec decoder for an Avro object container file; returns
    (records, header_meta)."""
    with open(path, "rb") as f:
        r = _Reader(f.read())
    if r.read(4) != _MAGIC:
        raise ValueError(f"{path}: not an Avro object container file")
    meta = {}
    while True:
        n = r.varint()
        if n == 0:
            break
        if n < 0:
            r.varint()
            n = -n
        for _ in range(n):
            k = r.string()
            meta[k] = r.bytes_()
    codec = meta.get("avro.codec", b"null")
    if codec not in (b"null",):
        raise ValueError(f"unsupported codec {codec!r}")
    schema = json.loads(meta["avro.schema"])
    sync = r.read(16)
    records = []
    while not r.at_end():
        count = r.varint()
        size = r.varint()
        block = _Reader(r.read(size))
        for _ in range(count):
            records.append(_decode(block, schema))
        if r.read(16) != sync:
            raise ValueError(f"{path}: sync marker mismatch")
    return records, meta


# ---------------------------------------------- Iceberg (from spec)

_STATUS_DELETED = 2


def current_metadata(table_path: str) -> dict:
    meta_dir = os.path.join(table_path, "metadata")
    with open(os.path.join(meta_dir, "version-hint.text")) as f:
        v = int(f.read().strip())
    with open(os.path.join(meta_dir, f"v{v}.metadata.json")) as f:
        return json.load(f)


def live_data_files(table_path: str) -> set:
    """The queryable file set per the Iceberg spec: walk the CURRENT
    snapshot's manifest list; within each data manifest keep entries
    whose status is ADDED(1) or EXISTING(0); DELETED(2) entries exist
    only for incremental consumers."""
    md = current_metadata(table_path)
    snap_id = md["current-snapshot-id"]
    if snap_id in (None, -1):
        return set()
    snap = next(s for s in md["snapshots"]
                if s["snapshot-id"] == snap_id)
    manifests, _ = read_avro_file(snap["manifest-list"])
    live = set()
    for m in manifests:
        entries, _ = read_avro_file(m["manifest_path"])
        for e in entries:
            if e["status"] == _STATUS_DELETED:
                continue
            live.add(e["data_file"]["file_path"])
    return live


def snapshot_lineage(table_path: str) -> list:
    """snapshot-ids in log order (metadata.json snapshot-log)."""
    md = current_metadata(table_path)
    return [s["snapshot-id"] for s in md.get("snapshot-log", [])]


def total_record_count(table_path: str) -> int:
    """Sum of record_count over live entries (cross-check against the
    Delta side's numRecords stats)."""
    md = current_metadata(table_path)
    snap_id = md["current-snapshot-id"]
    if snap_id in (None, -1):
        return 0
    snap = next(s for s in md["snapshots"]
                if s["snapshot-id"] == snap_id)
    manifests, _ = read_avro_file(snap["manifest-list"])
    total = 0
    for m in manifests:
        entries, _ = read_avro_file(m["manifest_path"])
        for e in entries:
            if e["status"] != _STATUS_DELETED:
                total += e["data_file"]["record_count"]
    return total

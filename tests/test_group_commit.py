"""Group commit: batch partitioning, threaded end-to-end batching, and
the ack-loss ambiguity ladder (per-member txnId read-back recovery)."""

import threading

import numpy as np
import pyarrow as pa
import pytest

import delta_tpu.api as dta
from delta_tpu import obs
from delta_tpu.engine.host import HostEngine
from delta_tpu.errors import (
    ConcurrentDeleteDeleteError,
    ConcurrentWriteError,
)
from delta_tpu.models.actions import AddFile
from delta_tpu.resilience.chaos import ChaosSchedule, ChaosStore
from delta_tpu.storage.logstore import InMemoryLogStore
from delta_tpu.table import Table
from delta_tpu.txn.groupcommit import (
    COMMITTED,
    REBASED,
    REJECTED,
    GroupCommitter,
    _Member,
    group_commit_enabled,
    group_committer_for,
)


def _batch(start, n):
    return pa.table({"id": pa.array(np.arange(start, start + n,
                                              dtype=np.int64))})


def _add(path, size=10):
    return AddFile(path=path, size=size, modificationTime=1,
                   dataChange=True)


def _counter(name):
    return obs.counter(name).value


# ---------------------------------------------------------------- _emit
# Deterministic batch partitioning: hand-built members through one
# _emit call, no threads, no window.


def test_batch_disjoint_members_all_commit(tmp_table_path):
    dta.write_table(tmp_table_path, _batch(0, 5))
    table = Table.for_path(tmp_table_path)
    gc = GroupCommitter(table, window_s=0.0)

    txns = []
    for i in range(3):
        t = table.start_transaction()
        t.add_file(_add(f"m{i}.parquet"))
        txns.append(t)
    members = [_Member(t) for t in txns]
    gc._emit(members)

    # all three commit; later members are typed REBASED because their
    # batch-mates took the slots between their read version and their
    # assigned version
    assert [m.outcome.kind for m in members] == [COMMITTED, REBASED,
                                                 REBASED]
    assert [m.outcome.version for m in members] == [1, 2, 3]
    snap = table.latest_snapshot()
    assert snap.version == 3
    paths = set(snap.state.add_files_table.column("path").to_pylist())
    assert {"m0.parquet", "m1.parquet", "m2.parquet"} <= paths


def test_batch_overlapping_members_split(tmp_table_path):
    """Delete-delete on the same file inside one batch: the first
    member wins (its actions become a pseudo-winner in the conflict
    set), ONLY the second is rejected, and an unrelated third member
    still commits — the batch never fails as a unit."""
    dta.write_table(tmp_table_path, _batch(0, 5))
    table = Table.for_path(tmp_table_path)
    victim = table.latest_snapshot().state.add_files()[0]
    gc = GroupCommitter(table, window_s=0.0)

    txn_a = table.start_transaction("DELETE")
    txn_a.remove_file(victim.remove(deletion_timestamp=1))
    txn_b = table.start_transaction("DELETE")
    txn_b.remove_file(victim.remove(deletion_timestamp=2))
    txn_c = table.start_transaction()
    txn_c.add_file(_add("c.parquet"))

    members = [_Member(t) for t in (txn_a, txn_b, txn_c)]
    gc._emit(members)

    assert members[0].outcome.kind == COMMITTED
    assert members[0].outcome.version == 1
    assert members[1].outcome.kind == REJECTED
    assert isinstance(members[1].outcome.error,
                      ConcurrentDeleteDeleteError)
    assert members[2].outcome.kind == REBASED  # past its batch-mate
    assert members[2].outcome.version == 2     # loser's slot not burned
    assert table.latest_snapshot().version == 2


def test_batch_domain_metadata_rejects_only_loser(tmp_table_path):
    from delta_tpu.commands.alter import upgrade_protocol

    dta.write_table(tmp_table_path, _batch(0, 5))
    table = Table.for_path(tmp_table_path)
    upgrade_protocol(table, feature="domainMetadata")  # -> v1
    gc = GroupCommitter(table, window_s=0.0)

    txn_a = table.start_transaction()
    txn_a.set_domain_metadata("d1", "a")
    txn_a.add_file(_add("a.parquet"))
    txn_b = table.start_transaction()
    txn_b.set_domain_metadata("d1", "b")  # same domain: loses to a
    txn_b.add_file(_add("b.parquet"))
    txn_c = table.start_transaction()
    txn_c.set_domain_metadata("d2", "c")  # disjoint domain: fine
    txn_c.add_file(_add("c.parquet"))

    members = [_Member(t) for t in (txn_a, txn_b, txn_c)]
    gc._emit(members)

    assert members[0].outcome.kind == COMMITTED
    assert members[1].outcome.kind == REJECTED
    assert isinstance(members[1].outcome.error, ConcurrentWriteError)
    assert members[2].outcome.kind == REBASED
    assert table.latest_snapshot().version == 3


def test_batch_stale_member_rebases(tmp_table_path):
    """A member whose read version is behind a landed winner rebases
    within the batch (typed REBASED, not a retry loop)."""
    dta.write_table(tmp_table_path, _batch(0, 5))
    table = Table.for_path(tmp_table_path)

    stale = table.start_transaction()
    stale.add_file(_add("stale.parquet"))
    # a solo writer lands v1 AFTER `stale` snapshotted v0
    winner = table.start_transaction()
    winner.add_file(_add("winner.parquet"))
    assert winner.commit().version == 1

    gc = GroupCommitter(table, window_s=0.0)
    members = [_Member(stale)]
    gc._emit(members)
    assert members[0].outcome.kind == REBASED
    assert members[0].outcome.version == 2


# ----------------------------------------------------- threaded batches


def test_group_commit_threaded_single_round_trip(tmp_table_path,
                                                 monkeypatch):
    monkeypatch.setenv("DELTA_TPU_GROUP_COMMIT", "1")
    monkeypatch.setenv("DELTA_TPU_GROUP_COMMIT_WINDOW_MS", "60")
    assert group_commit_enabled()
    dta.write_table(tmp_table_path, _batch(0, 5))
    table = Table.for_path(tmp_table_path)

    b0 = _counter("txn.group_commit.batches")
    m0 = _counter("txn.group_commit.members")
    txns = []
    for i in range(8):
        t = table.start_transaction()
        t.add_file(_add(f"w{i}.parquet"))
        txns.append(t)

    results, errors = [], []

    def commit(t):
        try:
            results.append(t.commit().version)
        except Exception as e:  # pragma: no cover - fail loudly below
            errors.append(e)

    threads = [threading.Thread(target=commit, args=(t,)) for t in txns]
    for th in threads:
        th.start()
    for th in threads:
        th.join()

    assert not errors
    assert sorted(results) == list(range(1, 9))  # gap-free, no dupes
    assert table.latest_snapshot().version == 8
    assert _counter("txn.group_commit.members") - m0 == 8
    # the whole burst rode ONE window (60ms >> thread startup skew)
    assert _counter("txn.group_commit.batches") - b0 == 1


def test_group_commit_disabled_by_default(tmp_table_path, monkeypatch):
    monkeypatch.delenv("DELTA_TPU_GROUP_COMMIT", raising=False)
    dta.write_table(tmp_table_path, _batch(0, 5))
    table = Table.for_path(tmp_table_path)
    assert group_committer_for(table) is None
    b0 = _counter("txn.group_commit.batches")
    txn = table.start_transaction()
    txn.add_file(_add("solo.parquet"))
    assert txn.commit().version == 1
    assert _counter("txn.group_commit.batches") == b0


def test_group_commit_max_batch_splits(tmp_table_path, monkeypatch):
    monkeypatch.setenv("DELTA_TPU_GROUP_COMMIT", "1")
    monkeypatch.setenv("DELTA_TPU_GROUP_COMMIT_WINDOW_MS", "40")
    monkeypatch.setenv("DELTA_TPU_GROUP_COMMIT_MAX_BATCH", "3")
    dta.write_table(tmp_table_path, _batch(0, 5))
    table = Table.for_path(tmp_table_path)
    b0 = _counter("txn.group_commit.batches")

    txns = []
    for i in range(6):
        t = table.start_transaction()
        t.add_file(_add(f"s{i}.parquet"))
        txns.append(t)
    results = []
    lock = threading.Lock()

    def commit(t):
        v = t.commit().version
        with lock:
            results.append(v)

    threads = [threading.Thread(target=commit, args=(t,)) for t in txns]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    assert sorted(results) == list(range(1, 7))
    assert _counter("txn.group_commit.batches") - b0 >= 2


# ------------------------------------------------------ ack-loss ladder


def test_group_commit_ack_loss_recovered_by_readback(monkeypatch):
    """Every batched emit's ack is lost after a random prefix of the
    batch lands (ChaosStore partial-batch ack loss): landed members are
    proven committed by per-member txnId read-back; the rest degrade to
    solo, whose own self-commit recovery is the backstop. Exactly-once:
    a gap-free log with every writer's file present exactly once."""
    monkeypatch.setenv("DELTA_TPU_GROUP_COMMIT", "1")
    monkeypatch.setenv("DELTA_TPU_GROUP_COMMIT_WINDOW_MS", "60")
    store = ChaosStore(InMemoryLogStore(),
                       ChaosSchedule(29, ack_loss_rate=1.0),
                       sleep=lambda s: None)
    eng = HostEngine(store_resolver=lambda path: store)
    path = "memory://group-ack-loss/tbl"
    dta.write_table(path, _batch(0, 5), engine=eng)
    table = Table.for_path(path, eng)

    r0 = _counter("txn.group_commit.readback_recovered")
    txns = []
    for i in range(6):
        t = table.start_transaction()
        t.add_file(_add(f"g{i}.parquet"))
        txns.append(t)
    results, errors = [], []
    lock = threading.Lock()

    def commit(t):
        try:
            v = t.commit().version
            with lock:
                results.append(v)
        except Exception as e:
            with lock:
                errors.append(e)

    threads = [threading.Thread(target=commit, args=(t,)) for t in txns]
    for th in threads:
        th.start()
    for th in threads:
        th.join()

    assert not errors
    assert store.fault_counts.get("batch_ack_loss", 0) > 0
    assert _counter("txn.group_commit.readback_recovered") > r0
    assert sorted(results) == list(range(1, 7))  # each exactly once
    store.enabled = False
    snap = Table.for_path(path, eng).latest_snapshot()
    assert snap.version == 6
    paths = [p for p in
             snap.state.add_files_table.column("path").to_pylist()
             if p.endswith(".parquet") and p.startswith("g")]
    assert sorted(paths) == [f"g{i}.parquet" for i in range(6)]


@pytest.mark.slow
def test_group_commit_ack_loss_soak_many_seeds():
    """Soak: 20 seeded partial-batch ack-loss schedules, each
    converging to a gap-free log with every member exactly once."""
    import os

    os.environ["DELTA_TPU_GROUP_COMMIT"] = "1"
    os.environ["DELTA_TPU_GROUP_COMMIT_WINDOW_MS"] = "40"
    try:
        for seed in range(20):
            store = ChaosStore(InMemoryLogStore(),
                               ChaosSchedule(seed, ack_loss_rate=0.5,
                                             error_rate=0.05),
                               sleep=lambda s: None)
            eng = HostEngine(store_resolver=lambda path: store)
            path = f"memory://group-soak-{seed}/tbl"
            dta.write_table(path, _batch(0, 5), engine=eng)
            table = Table.for_path(path, eng)
            txns = []
            for i in range(5):
                t = table.start_transaction()
                t.add_file(_add(f"g{i}.parquet"))
                txns.append(t)
            errs = []

            def commit(t):
                try:
                    t.commit()
                except Exception as e:  # pragma: no cover
                    errs.append(e)

            threads = [threading.Thread(target=commit, args=(t,))
                       for t in txns]
            for th in threads:
                th.start()
            for th in threads:
                th.join()
            assert not errs, f"seed {seed}: {errs}"
            store.enabled = False
            snap = Table.for_path(path, eng).latest_snapshot()
            assert snap.version == 5, f"seed {seed}"
            paths = [p for p in
                     snap.state.add_files_table.column("path").to_pylist()
                     if p.startswith("g")]
            assert sorted(paths) == [f"g{i}.parquet" for i in range(5)], \
                f"duplicate or missing member under seed {seed}"
    finally:
        os.environ.pop("DELTA_TPU_GROUP_COMMIT", None)
        os.environ.pop("DELTA_TPU_GROUP_COMMIT_WINDOW_MS", None)

"""Deterministic two-writer races via the phase-locking observer, plus
coordinated-commit behavior."""

import numpy as np
import pyarrow as pa
import pytest

import delta_tpu.api as dta
from delta_tpu.concurrency import PhaseLockingObserver, run_txn_async
from delta_tpu.errors import (
    ConcurrentAppendError,
    ConcurrentDeleteDeleteError,
    ConcurrentTransactionError,
    MetadataChangedError,
)
from delta_tpu.models.actions import AddFile
from delta_tpu.table import Table
from delta_tpu.txn.isolation import IsolationLevel


def _batch(start, n):
    return pa.table({"id": pa.array(np.arange(start, start + n, dtype=np.int64))})


def _add(path, size=10):
    return AddFile(path=path, size=size, modificationTime=1, dataChange=True)


def test_blind_append_race_rebases(tmp_table_path):
    dta.write_table(tmp_table_path, _batch(0, 5))
    table = Table.for_path(tmp_table_path)

    obs = PhaseLockingObserver(block_before_commit=True)
    txn_a = table.start_transaction()
    txn_a.add_file(_add("a.parquet"))
    txn_a.observer = obs
    thread = run_txn_async(txn_a.commit)
    obs.before_commit_barrier.wait_for_arrival()

    # B wins the race while A is parked before its write
    txn_b = table.start_transaction()
    txn_b.add_file(_add("b.parquet"))
    res_b = txn_b.commit()
    assert res_b.version == 1

    obs.before_commit_barrier.unblock()
    res_a = thread.join_result()
    assert res_a.version == 2          # rebased past B
    assert res_a.attempts == 2
    kinds = [k for k, _ in obs.events]
    assert kinds == ["attempt", "prepared", "conflict",
                     "attempt", "prepared", "committed"]

    snap = table.latest_snapshot()
    paths = set(snap.state.add_files_table.column("path").to_pylist())
    assert {"a.parquet", "b.parquet"} <= paths


def test_delete_delete_conflict(tmp_table_path):
    dta.write_table(tmp_table_path, _batch(0, 5))
    table = Table.for_path(tmp_table_path)
    victim = table.latest_snapshot().state.add_files()[0]

    obs = PhaseLockingObserver(block_before_commit=True)
    txn_a = table.start_transaction("DELETE")
    txn_a.remove_file(victim.remove(deletion_timestamp=1))
    txn_a.observer = obs
    thread = run_txn_async(txn_a.commit)
    obs.before_commit_barrier.wait_for_arrival()

    txn_b = table.start_transaction("DELETE")
    txn_b.remove_file(victim.remove(deletion_timestamp=2))
    txn_b.commit()

    obs.before_commit_barrier.unblock()
    with pytest.raises(ConcurrentDeleteDeleteError):
        thread.join_result()


def test_read_append_conflict_serializable(tmp_table_path):
    dta.write_table(tmp_table_path, _batch(0, 5))
    table = Table.for_path(tmp_table_path)

    txn_a = table.start_transaction()
    txn_a._isolation = IsolationLevel.SERIALIZABLE
    txn_a.scan_files()  # reads whole table
    txn_a.add_file(_add("a2.parquet"))

    txn_b = table.start_transaction()
    txn_b.add_file(_add("b2.parquet"))
    txn_b.commit()

    with pytest.raises(ConcurrentAppendError):
        txn_a.commit()


def test_blind_append_no_conflict_write_serializable(tmp_table_path):
    """Under WriteSerializable a blind append doesn't conflict with a
    reader's snapshot."""
    dta.write_table(tmp_table_path, _batch(0, 5))
    table = Table.for_path(tmp_table_path)

    txn_a = table.start_transaction()
    txn_a.scan_files()
    txn_a.add_file(_add("a3.parquet"))

    txn_b = table.start_transaction()  # blind append
    txn_b.add_file(_add("b3.parquet"))
    txn_b.commit()

    res = txn_a.commit()  # WriteSerializable default: rebase succeeds
    assert res.version == 2


def test_metadata_change_conflict(tmp_table_path):
    dta.write_table(tmp_table_path, _batch(0, 5))
    table = Table.for_path(tmp_table_path)
    import dataclasses

    txn_a = table.start_transaction()
    txn_a.add_file(_add("x.parquet"))

    txn_b = table.start_transaction("SET TBLPROPERTIES")
    meta = txn_b.metadata()
    txn_b.update_metadata(
        dataclasses.replace(
            meta, configuration={**meta.configuration, "foo": "bar"}
        )
    )
    txn_b.commit()

    with pytest.raises(MetadataChangedError):
        txn_a.commit()


def test_set_transaction_conflict(tmp_table_path):
    dta.write_table(tmp_table_path, _batch(0, 5))
    table = Table.for_path(tmp_table_path)

    txn_a = table.start_transaction()
    txn_a.set_transaction_id("app1", 5)
    txn_a.add_file(_add("y.parquet"))

    txn_b = table.start_transaction()
    txn_b.set_transaction_id("app1", 4)
    txn_b.add_file(_add("z.parquet"))
    txn_b.commit()

    with pytest.raises(ConcurrentTransactionError):
        txn_a.commit()


# ---------------------------------------------------------------------------
# coordinated commits
# ---------------------------------------------------------------------------


def test_coordinated_commit_unbackfilled_reads(coordinated_path):
    import os

    table = Table.for_path(coordinated_path)
    dta.write_table(coordinated_path, _batch(5, 5))   # v1 -> unbackfilled
    dta.write_table(coordinated_path, _batch(10, 5))  # v2 -> unbackfilled
    log_dir = os.path.join(coordinated_path, "_delta_log")
    backfilled = [f for f in os.listdir(log_dir) if f.endswith(".json") and "." not in f[:-5]]
    # v1, v2 not yet backfilled (batch_size=3), but reads see them
    assert not os.path.exists(os.path.join(log_dir, "00000000000000000002.json"))
    out = dta.read_table(coordinated_path)
    assert out.num_rows == 15
    snap = Table.for_path(coordinated_path).latest_snapshot()
    assert snap.version == 2
    # v3 triggers batch backfill
    dta.write_table(coordinated_path, _batch(15, 5))
    assert os.path.exists(os.path.join(log_dir, "00000000000000000003.json"))
    assert dta.read_table(coordinated_path).num_rows == 20


def test_coordinated_commit_race(coordinated_path):
    table = Table.for_path(coordinated_path)
    obs = PhaseLockingObserver(block_before_commit=True)
    txn_a = table.start_transaction()
    txn_a.add_file(_add("ca.parquet"))
    txn_a.observer = obs
    thread = run_txn_async(txn_a.commit)
    obs.before_commit_barrier.wait_for_arrival()

    txn_b = Table.for_path(coordinated_path).start_transaction()
    txn_b.add_file(_add("cb.parquet"))
    vb = txn_b.commit().version

    obs.before_commit_barrier.unblock()
    res_a = thread.join_result()
    assert res_a.version == vb + 1
    snap = Table.for_path(coordinated_path).latest_snapshot()
    paths = set(snap.state.add_files_table.column("path").to_pylist())
    assert {"ca.parquet", "cb.parquet"} <= paths


def test_append_only_commit_backstop(tmp_table_path):
    """A raw transaction with a data-changing remove must be rejected on
    an appendOnly table at commit (DeltaLog.assertRemovable), while
    dataChange=false rewrites stay allowed."""
    import numpy as np
    import pyarrow as pa
    import pytest

    import delta_tpu.api as dta
    from delta_tpu.errors import DeltaError
    from delta_tpu.table import Table

    dta.write_table(tmp_table_path, pa.table(
        {"x": pa.array(np.arange(5, dtype=np.int64))}),
        properties={"delta.appendOnly": "true"})
    t = Table.for_path(tmp_table_path)
    snap = t.latest_snapshot()
    add = snap.state.add_files()[0]

    txn = t.start_transaction("DELETE")
    txn.remove_file(add.remove(deletion_timestamp=1, data_change=True))
    with pytest.raises(DeltaError, match="only allow appends"):
        txn.commit()

    # dataChange=false (compaction-style) remove is fine
    txn2 = t.start_transaction("OPTIMIZE")
    txn2.remove_file(add.remove(deletion_timestamp=1, data_change=False))
    txn2.add_files([add])
    txn2.commit()


# ---- stats-based conflict elimination (ConflictChecker.scala:584) ----

def _stats_json(lo, hi, n=5, nulls=0):
    import json

    return json.dumps({"numRecords": n, "minValues": {"v": lo},
                       "maxValues": {"v": hi}, "nullCount": {"v": nulls}})


def _vtable(path):
    dta.write_table(path, pa.table({
        "v": pa.array([1.0, 2.0, 3.0], pa.float64())}))
    return Table.for_path(path)


def test_append_disjoint_stats_does_not_conflict(tmp_table_path):
    """SERIALIZABLE + a non-partition read predicate: a concurrent
    append whose stats range is disjoint from the predicate must NOT
    abort — the winner's min/max disprove overlap."""
    from delta_tpu.expressions.tree import col, lit

    table = _vtable(tmp_table_path)
    txn_a = table.start_transaction()
    txn_a._isolation = IsolationLevel.SERIALIZABLE
    txn_a.scan_files(filter=col("v") < lit(0.5))
    txn_a.add_file(_add("a.parquet"))

    txn_b = table.start_transaction()
    txn_b.add_file(AddFile(
        path="hi.parquet", size=10, modificationTime=1,
        dataChange=True, stats=_stats_json(100.0, 200.0)))
    txn_b.commit()

    res = txn_a.commit()  # rebases instead of aborting
    assert res.version == 2 and res.attempts == 2


def test_append_overlapping_stats_conflicts(tmp_table_path):
    from delta_tpu.expressions.tree import col, lit

    table = _vtable(tmp_table_path)
    txn_a = table.start_transaction()
    txn_a._isolation = IsolationLevel.SERIALIZABLE
    txn_a.scan_files(filter=col("v") < lit(0.5))
    txn_a.add_file(_add("a.parquet"))

    txn_b = table.start_transaction()
    txn_b.add_file(AddFile(
        path="lo.parquet", size=10, modificationTime=1,
        dataChange=True, stats=_stats_json(0.0, 1.0)))
    txn_b.commit()

    with pytest.raises(ConcurrentAppendError):
        txn_a.commit()


def test_append_without_stats_stays_pessimistic(tmp_table_path):
    from delta_tpu.expressions.tree import col, lit

    table = _vtable(tmp_table_path)
    txn_a = table.start_transaction()
    txn_a._isolation = IsolationLevel.SERIALIZABLE
    txn_a.scan_files(filter=col("v") < lit(0.5))
    txn_a.add_file(_add("a.parquet"))

    txn_b = table.start_transaction()
    txn_b.add_file(_add("nostats.parquet"))  # no stats -> can't disprove
    txn_b.commit()

    with pytest.raises(ConcurrentAppendError):
        txn_a.commit()


def test_conjunct_widening_uses_evaluable_part(tmp_table_path):
    """(v < 0.5) AND (unevaluable): the evaluable conjunct alone can
    disprove; the unevaluable one widens to true instead of forcing a
    conflict (ConflictCheckerPredicateElimination.scala:30 role)."""
    from delta_tpu.expressions.tree import And, Comparison, col, lit

    table = _vtable(tmp_table_path)
    pred = And(Comparison("<", col("v"), lit(0.5)),
               Comparison("=", col("w"), lit("?")))  # w: no stats
    txn_a = table.start_transaction()
    txn_a._isolation = IsolationLevel.SERIALIZABLE
    txn_a.scan_files(filter=pred)
    txn_a.add_file(_add("a.parquet"))

    txn_b = table.start_transaction()
    txn_b.add_file(AddFile(
        path="hi.parquet", size=10, modificationTime=1,
        dataChange=True, stats=_stats_json(100.0, 200.0)))
    txn_b.commit()

    res = txn_a.commit()
    assert res.version == 2


def test_real_write_stats_eliminate_conflict(tmp_table_path):
    """End-to-end: the stats collected by the real writer (not crafted
    JSON) drive the elimination."""
    from delta_tpu.expressions.tree import col, lit

    table = _vtable(tmp_table_path)
    txn_a = table.start_transaction()
    txn_a._isolation = IsolationLevel.SERIALIZABLE
    txn_a.scan_files(filter=col("v") < lit(0.5))
    txn_a.add_file(_add("a.parquet"))

    # real append with genuinely disjoint values
    dta.write_table(tmp_table_path, pa.table({
        "v": pa.array([500.0, 600.0], pa.float64())}), mode="append")

    res = txn_a.commit()
    assert res.version == 2

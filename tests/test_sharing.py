"""Delta Sharing client with a fake transport backed by a real local table."""

import json
import os

import numpy as np
import pyarrow as pa

import delta_tpu.api as dta
from delta_tpu.interop.sharing import (
    ShareProfile,
    SharingClient,
    load_shared_table,
    materialize_shared_table,
)
from delta_tpu.table import Table


def _server_for(table_path):
    """Fake sharing server: serves one table from a local delta table,
    speaking the sharing wire format (urls = local absolute paths). The
    snapshot is resolved per query, so appends to the backing table show
    up on the next poll (as on a real server)."""

    def transport(path, body):
        snap = Table.for_path(table_path).latest_snapshot()
        meta = snap.metadata
        if path == "/shares":
            return {"items": [{"name": "s1"}]}
        if path == "/shares/s1/schemas":
            return {"items": [{"name": "default"}]}
        if path == "/shares/s1/schemas/default/tables":
            return {"items": [{"name": "t1"}]}
        if path.endswith("/query"):
            lines = [
                {"protocol": {"minReaderVersion": 1}},
                {
                    "metaData": {
                        "id": meta.id,
                        "format": {"provider": "parquet"},
                        "schemaString": meta.schemaString,
                        "partitionColumns": meta.partitionColumns,
                    }
                },
            ]
            for f in snap.state.add_files():
                lines.append(
                    {
                        "file": {
                            "url": os.path.join(table_path, f.path),
                            "id": f.path,
                            "partitionValues": f.partitionValues,
                            "size": f.size,
                            "stats": f.stats,
                        }
                    }
                )
            return {"lines": [json.dumps(l) for l in lines]}
        raise AssertionError(path)

    return transport


def test_sharing_end_to_end(tmp_table_path, tmp_path):
    data = pa.table({"id": pa.array(np.arange(50, dtype=np.int64))})
    dta.write_table(tmp_table_path, data)
    client = SharingClient(ShareProfile(endpoint="http://fake"), _server_for(tmp_table_path))
    assert client.list_shares() == ["s1"]
    assert client.list_schemas("s1") == ["default"]
    assert client.list_tables("s1", "default") == ["t1"]

    shared = load_shared_table(
        client, "s1", "default", "t1", workdir=str(tmp_path / "shared")
    )
    snap = shared.latest_snapshot()
    assert snap.num_files == 1
    out = snap.scan().to_arrow()
    assert out.num_rows == 50
    assert sorted(out.column("id").to_pylist()) == list(range(50))


def test_sharing_stats_skipping(tmp_table_path, tmp_path):
    data = pa.table({"id": pa.array(np.arange(100, dtype=np.int64))})
    dta.write_table(tmp_table_path, data, target_rows_per_file=20)
    client = SharingClient(ShareProfile(endpoint="x"), _server_for(tmp_table_path))
    shared = load_shared_table(
        client, "s1", "default", "t1", workdir=str(tmp_path / "shared")
    )
    from delta_tpu.expressions import col, lit

    scan = shared.latest_snapshot().scan(filter=col("id") < lit(20))
    assert scan.add_files_table().num_rows == 1  # stats carried through
    assert scan.to_arrow().num_rows == 20


def test_sharing_stream_source(tmp_table_path, tmp_path):
    from delta_tpu.interop.sharing import SharingStreamSource

    dta.write_table(tmp_table_path, pa.table(
        {"id": pa.array(np.arange(10, dtype=np.int64))}))
    client = SharingClient(
        ShareProfile(endpoint="fake", bearer_token="t"),
        _server_for(tmp_table_path))
    src = SharingStreamSource(client, "s1", "default", "t1",
                              workdir=str(tmp_path / "stream"))

    rows, n = src.poll()
    assert n == 1 and sorted(rows.column("id").to_pylist()) == list(range(10))
    # caught up: next poll yields nothing
    assert src.poll() == (None, 0)

    # append server-side; only the new file arrives on the next poll
    dta.write_table(tmp_table_path, pa.table(
        {"id": pa.array(np.arange(10, 20, dtype=np.int64))}), mode="append")
    batches = list(src.micro_batches())
    assert len(batches) == 1
    rows2, n2 = batches[0]
    assert n2 == 1
    assert sorted(rows2.column("id").to_pylist()) == list(range(10, 20))


def test_sharing_stream_rejects_rewrites(tmp_table_path, tmp_path):
    from delta_tpu.commands.dml import delete
    from delta_tpu.expressions import col, lit
    from delta_tpu.errors import DeltaError
    from delta_tpu.interop.sharing import SharingStreamSource
    import pytest as _pytest

    dta.write_table(tmp_table_path, pa.table(
        {"id": pa.array(np.arange(10, dtype=np.int64))}))
    client = SharingClient(
        ShareProfile(endpoint="fake", bearer_token="t"),
        _server_for(tmp_table_path))
    src = SharingStreamSource(client, "s1", "default", "t1",
                              workdir=str(tmp_path / "s"))
    src.poll()
    # server-side rewrite: delete removes rows -> file replaced
    delete(Table.for_path(tmp_table_path), predicate=col("id") < lit(5))
    with _pytest.raises(DeltaError):
        src.poll()
    # with ignore_changes the rewritten file is re-emitted
    src2 = SharingStreamSource(client, "s1", "default", "t1",
                               workdir=str(tmp_path / "s2"),
                               ignore_changes=True)
    rows, n = src2.poll()
    assert rows.num_rows == 5

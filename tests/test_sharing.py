"""Delta Sharing client with a fake transport backed by a real local table."""

import json
import os

import numpy as np
import pyarrow as pa

import delta_tpu.api as dta
from delta_tpu.interop.sharing import (
    ShareProfile,
    SharingClient,
    load_shared_table,
    materialize_shared_table,
)
from delta_tpu.table import Table


def _server_for(table_path):
    """Fake sharing server: serves one table from a local delta table,
    speaking the sharing wire format (urls = local absolute paths). The
    snapshot is resolved per query, so appends to the backing table show
    up on the next poll (as on a real server)."""

    def transport(path, body):
        snap = Table.for_path(table_path).latest_snapshot()
        meta = snap.metadata
        if path == "/shares":
            return {"items": [{"name": "s1"}]}
        if path == "/shares/s1/schemas":
            return {"items": [{"name": "default"}]}
        if path == "/shares/s1/schemas/default/tables":
            return {"items": [{"name": "t1"}]}
        if path.endswith("/query"):
            lines = [
                {"protocol": {"minReaderVersion": 1}},
                {
                    "metaData": {
                        "id": meta.id,
                        "format": {"provider": "parquet"},
                        "schemaString": meta.schemaString,
                        "partitionColumns": meta.partitionColumns,
                    }
                },
            ]
            for f in snap.state.add_files():
                lines.append(
                    {
                        "file": {
                            "url": os.path.join(table_path, f.path),
                            "id": f.path,
                            "partitionValues": f.partitionValues,
                            "size": f.size,
                            "stats": f.stats,
                        }
                    }
                )
            return {"lines": [json.dumps(l) for l in lines]}
        raise AssertionError(path)

    return transport


def test_sharing_end_to_end(tmp_table_path, tmp_path):
    data = pa.table({"id": pa.array(np.arange(50, dtype=np.int64))})
    dta.write_table(tmp_table_path, data)
    client = SharingClient(ShareProfile(endpoint="http://fake"), _server_for(tmp_table_path))
    assert client.list_shares() == ["s1"]
    assert client.list_schemas("s1") == ["default"]
    assert client.list_tables("s1", "default") == ["t1"]

    shared = load_shared_table(
        client, "s1", "default", "t1", workdir=str(tmp_path / "shared")
    )
    snap = shared.latest_snapshot()
    assert snap.num_files == 1
    out = snap.scan().to_arrow()
    assert out.num_rows == 50
    assert sorted(out.column("id").to_pylist()) == list(range(50))


def test_sharing_stats_skipping(tmp_table_path, tmp_path):
    data = pa.table({"id": pa.array(np.arange(100, dtype=np.int64))})
    dta.write_table(tmp_table_path, data, target_rows_per_file=20)
    client = SharingClient(ShareProfile(endpoint="x"), _server_for(tmp_table_path))
    shared = load_shared_table(
        client, "s1", "default", "t1", workdir=str(tmp_path / "shared")
    )
    from delta_tpu.expressions import col, lit

    scan = shared.latest_snapshot().scan(filter=col("id") < lit(20))
    assert scan.add_files_table().num_rows == 1  # stats carried through
    assert scan.to_arrow().num_rows == 20


def test_sharing_stream_source(tmp_table_path, tmp_path):
    from delta_tpu.interop.sharing import SharingStreamSource

    dta.write_table(tmp_table_path, pa.table(
        {"id": pa.array(np.arange(10, dtype=np.int64))}))
    client = SharingClient(
        ShareProfile(endpoint="fake", bearer_token="t"),
        _server_for(tmp_table_path))
    src = SharingStreamSource(client, "s1", "default", "t1",
                              workdir=str(tmp_path / "stream"))

    rows, n = src.poll()
    assert n == 1 and sorted(rows.column("id").to_pylist()) == list(range(10))
    # caught up: next poll yields nothing
    assert src.poll() == (None, 0)

    # append server-side; only the new file arrives on the next poll
    dta.write_table(tmp_table_path, pa.table(
        {"id": pa.array(np.arange(10, 20, dtype=np.int64))}), mode="append")
    batches = list(src.micro_batches())
    assert len(batches) == 1
    rows2, n2 = batches[0]
    assert n2 == 1
    assert sorted(rows2.column("id").to_pylist()) == list(range(10, 20))


def test_sharing_stream_rejects_rewrites(tmp_table_path, tmp_path):
    from delta_tpu.commands.dml import delete
    from delta_tpu.expressions import col, lit
    from delta_tpu.errors import DeltaError
    from delta_tpu.interop.sharing import SharingStreamSource
    import pytest as _pytest

    dta.write_table(tmp_table_path, pa.table(
        {"id": pa.array(np.arange(10, dtype=np.int64))}))
    client = SharingClient(
        ShareProfile(endpoint="fake", bearer_token="t"),
        _server_for(tmp_table_path))
    src = SharingStreamSource(client, "s1", "default", "t1",
                              workdir=str(tmp_path / "s"))
    src.poll()
    # server-side rewrite: delete removes rows -> file replaced
    delete(Table.for_path(tmp_table_path), predicate=col("id") < lit(5))
    with _pytest.raises(DeltaError):
        src.poll()
    # with ignore_changes the rewritten file is re-emitted
    src2 = SharingStreamSource(client, "s1", "default", "t1",
                               workdir=str(tmp_path / "s2"),
                               ignore_changes=True)
    rows, n = src2.poll()
    assert rows.num_rows == 5


# ------------------------------------------------- real HTTP transport


def _start_mock_server(table_path):
    """Real local HTTP server speaking the Delta Sharing REST protocol,
    backed by a live local delta table. Exercises: bearer auth, list
    pagination (nextPageToken), the /version header endpoint, ndjson
    /query responses, and one injected 429 to prove retry."""
    import http.server
    import threading

    state = {"flaky": 1, "auth_seen": []}

    class Handler(http.server.BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def _json(self, obj, version=None):
            body = json.dumps(obj).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            if version is not None:
                self.send_header("Delta-Table-Version", str(version))
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            state["auth_seen"].append(self.headers.get("Authorization"))
            snap = Table.for_path(table_path).latest_snapshot()
            if self.path == "/base/shares":
                self._json({"items": [{"name": "s1"}],
                            "nextPageToken": "p2"})
            elif self.path == "/base/shares?pageToken=p2":
                self._json({"items": [{"name": "s2"}]})
            elif self.path == "/base/shares/s1/schemas":
                self._json({"items": [{"name": "default"}]})
            elif self.path == "/base/shares/s1/schemas/default/tables":
                self._json({"items": [{"name": "t1"}]})
            elif self.path.endswith("/tables/t1/version"):
                self._json({}, version=snap.version)
            else:
                self.send_error(404)

        def do_POST(self):
            state["auth_seen"].append(self.headers.get("Authorization"))
            if state["flaky"] > 0:
                state["flaky"] -= 1
                self.send_response(429)
                self.send_header("Retry-After", "0")
                self.end_headers()
                return
            snap = Table.for_path(table_path).latest_snapshot()
            meta = snap.metadata
            lines = [
                {"protocol": {"minReaderVersion": 1}},
                {"metaData": {
                    "id": meta.id,
                    "format": {"provider": "parquet"},
                    "schemaString": meta.schemaString,
                    "partitionColumns": meta.partitionColumns,
                }},
            ]
            for f in snap.state.add_files():
                lines.append({"file": {
                    "url": os.path.join(table_path, f.path),
                    "id": f.path,
                    "partitionValues": f.partitionValues,
                    "size": f.size,
                    "stats": f.stats,
                }})
            body = "\n".join(json.dumps(l) for l in lines).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/x-ndjson")
            self.send_header("Delta-Table-Version", str(snap.version))
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), Handler)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    return srv, state


def test_sharing_http_transport_end_to_end(tmp_table_path, tmp_path):
    from delta_tpu.interop.sharing import HttpTransport, SharingStreamSource

    dta.write_table(tmp_table_path, pa.table(
        {"id": pa.array(np.arange(30, dtype=np.int64))}))
    srv, state = _start_mock_server(tmp_table_path)
    try:
        port = srv.server_address[1]
        profile = ShareProfile(
            endpoint=f"http://127.0.0.1:{port}/base", bearer_token="tok123")
        client = SharingClient(profile)  # default transport = HTTP
        assert isinstance(client.transport, HttpTransport)

        # pagination drains both pages
        assert client.list_shares() == ["s1", "s2"]
        assert client.list_schemas("s1") == ["default"]
        assert client.list_tables("s1", "default") == ["t1"]
        # version endpoint reads the response header
        assert client.table_version("s1", "default", "t1") == 0

        # query (with one injected 429 retried transparently)
        shared = load_shared_table(
            client, "s1", "default", "t1", workdir=str(tmp_path / "sh"))
        out = shared.latest_snapshot().scan().to_arrow()
        assert sorted(out.column("id").to_pylist()) == list(range(30))
        assert all(a == "Bearer tok123" for a in state["auth_seen"])

        # streaming over real HTTP: append shows up on next poll
        src = SharingStreamSource(client, "s1", "default", "t1",
                                  workdir=str(tmp_path / "stream"))
        rows, n = src.poll()
        assert n == 1 and rows.num_rows == 30
        assert src.poll() == (None, 0)
        dta.write_table(tmp_table_path, pa.table(
            {"id": pa.array(np.arange(30, 40, dtype=np.int64))}),
            mode="append")
        rows2, n2 = src.poll()
        assert n2 == 1
        assert sorted(rows2.column("id").to_pylist()) == list(range(30, 40))
    finally:
        srv.shutdown()


def test_sharing_http_error_surface(tmp_path):
    from delta_tpu.errors import DeltaError
    from delta_tpu.interop.sharing import HttpTransport
    import pytest as _pytest

    # unreachable server -> DeltaError, not a raw socket error
    profile = ShareProfile(endpoint="http://127.0.0.1:9", bearer_token="")
    t = HttpTransport(profile, timeout=0.2, max_retries=0)
    with _pytest.raises(DeltaError, match="unreachable"):
        t("/shares", None)

"""DeviceSpine bridge regressions (`sqlengine/device.py`): semantics
where the device path could silently diverge from the pandas parity
oracle. Corpus-level parity lives in test_tpcds.py (both substrates);
kernel-level parity in test_sqlops.py."""

import numpy as np
import pandas as pd
import pytest

import delta_tpu.api as dta
import pyarrow as pa
from delta_tpu.sqlengine.device import DeviceSpine, spine_for


class _F:
    """Minimal Func stand-in for direct groupby() calls."""

    def __init__(self, name, star=False, distinct=False):
        self.name = name
        self.star = star
        self.distinct = distinct
        self.args = [None]


@pytest.fixture(scope="module")
def spine():
    return DeviceSpine()


@pytest.mark.parametrize("unit", ["s", "ms", "us", "ns"])
def test_groupby_datetime_units(spine, unit):
    # non-ns datetime columns must not leak raw ticks through the
    # .view("datetime64[ns]") reconstruction
    dates = np.array(["2020-06-01", "2019-01-02", "2021-03-04"],
                     dtype=f"datetime64[{unit}]")
    work = pd.DataFrame({"g": [0, 0, 0], "__arg_k": dates})
    out = spine.groupby(work, ["g"], {"k": _F("max")})
    assert pd.Timestamp(out["k"].iloc[0]) == pd.Timestamp("2021-03-04")
    out = spine.groupby(work, ["g"], {"k": _F("min")})
    assert pd.Timestamp(out["k"].iloc[0]) == pd.Timestamp("2019-01-02")


def test_partition_sum_all_null_is_null(spine):
    # SQL: SUM over an all-NULL partition is NULL — device returns NaN
    s = pd.Series([np.nan, np.nan, 1.0])
    parts = [pd.Series([0, 0, 1])]
    r = spine.partition_transform(parts, s, "sum")
    assert np.isnan(r.iloc[0]) and np.isnan(r.iloc[1])
    assert r.iloc[2] == 1.0


def test_window_sum_all_null_parity(tmp_path):
    # both substrates must agree on the all-NULL-partition window SUM
    from delta_tpu.engine.host import HostEngine
    from delta_tpu.engine.tpu import TpuEngine
    from delta_tpu.sql import sql

    p = str(tmp_path / "t")
    dta.write_table(p, pa.table({
        "g": pa.array([0, 0, 1], pa.int64()),
        "v": pa.array([None, None, 1.0], pa.float64()),
    }))
    q = f"SELECT g, sum(v) OVER (PARTITION BY g) AS s FROM '{p}' ORDER BY g"
    dev = sql(q, engine=TpuEngine())
    host = sql(q, engine=HostEngine())
    assert dev.column("s").to_pylist() == host.column("s").to_pylist() \
        == [None, None, 1.0]


def test_spine_resolution():
    from delta_tpu.engine.host import HostEngine
    from delta_tpu.engine.tpu import TpuEngine

    assert spine_for(TpuEngine()) is not None
    assert spine_for(HostEngine()) is None
    assert spine_for(None) is not None  # default engine is TpuEngine


def test_spine_env_override(monkeypatch):
    from delta_tpu.engine.host import HostEngine
    from delta_tpu.engine.tpu import TpuEngine

    monkeypatch.setenv("DELTA_TPU_DEVICE_SQL", "0")
    assert spine_for(TpuEngine()) is None
    monkeypatch.setenv("DELTA_TPU_DEVICE_SQL", "1")
    assert spine_for(HostEngine()) is not None


def test_merge_null_extension_dtypes(spine):
    # left-join null extension must upcast like pandas (int -> float)
    left = pd.DataFrame({"a.k": [1, 2, 3], "a.x": [10, 20, 30]})
    right = pd.DataFrame({"b.k": [1, 1], "b.y": [5, 6]})
    out = spine.merge(left, right, "left", ["a.k"], ["b.k"])
    assert len(out) == 4  # k=1 matches twice, k=2/k=3 null-extended
    nulls = out[out["b.y"].isna()]
    assert sorted(nulls["a.k"].tolist()) == [2, 3]
    ref = left.merge(right, how="left", left_on=["a.k"],
                     right_on=["b.k"])
    assert sorted(map(tuple, out.fillna(-1).to_numpy().tolist())) == \
        sorted(map(tuple, ref.fillna(-1).to_numpy().tolist()))


def test_groupby_string_min_falls_back(spine):
    # object-dtype aggregation is unsupported -> None (pandas handles)
    work = pd.DataFrame({"g": [0, 1], "__arg_k": ["b", "a"]})
    assert spine.groupby(work, ["g"], {"k": _F("min")}) is None

"""Two-process distributed replay over a global mesh (jax.distributed).

The multi-host story from the module docstring of
`parallel/sharded_replay.py`, actually executed: two OS processes, each
with 4 virtual CPU devices, form one 8-device global mesh via
`jax.distributed.initialize`. Each process routes ONLY the rows it
"parsed" (keys are pre-partitioned by `key % 2 == process_id`, the way a
multi-host columnarizer would split commit files), provides its local
[4, M] shard blocks with `jax.make_array_from_process_local_data`, and
runs the same shard_map replay kernel. The `psum` aggregate crosses the
process boundary (Gloo collectives on CPU; ICI/DCN on real TPU pods) and
must equal the global sequential reference on BOTH processes; each
process additionally verifies the winner masks of its own rows.

The subprocesses strip the axon sitecustomize (PYTHONPATH) so the CPU
platform initializes fresh — mirroring how a real multi-host job
launches one process per host before any jax import.
"""

import os
import socket
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# tier-2: real jax.distributed two-process jobs (Gloo rendezvous + full
# XLA re-init per process) take minutes on constrained hosts; the tier-1
# sharded coverage lives in test_sharded_replay.py on the in-process
# 8-emulated-device mesh (the `sharded8` lane)
pytestmark = pytest.mark.slow

WORKER = r"""
import os, sys
pid = int(sys.argv[1])
port = sys.argv[2]
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
import jax
jax.distributed.initialize(
    coordinator_address=f"127.0.0.1:{port}", num_processes=2, process_id=pid)
import numpy as np
sys.path.insert(0, {repo!r})
from jax.sharding import NamedSharding, PartitionSpec as P
from delta_tpu.ops.replay import python_replay_reference
from delta_tpu.parallel.mesh import REPLAY_AXIS, make_mesh
from delta_tpu.parallel.sharded_replay import build_sharded_replay_fn

assert len(jax.devices()) == 8, len(jax.devices())
assert len(jax.local_devices()) == 4

# deterministic GLOBAL history, identical in both processes
rng = np.random.default_rng(0)
n = 20_000
key = rng.integers(0, 3000, n).astype(np.uint32)
ver = np.sort(rng.integers(0, 64, n)).astype(np.int32)
add = rng.random(n) < 0.6
size = rng.integers(100, 1000, n).astype(np.int64)

# this process's rows (the files its host "parsed"); shard assignment is
# process = key % 2, local shard = (key // 2) % 4 — injective per key, so
# per-shard dedup is globally correct with no cross-device key exchange
mine = key % 2 == pid
lk, la, ls = key[mine], add[mine], size[mine]
n_local = int(mine.sum())
local_shard = ((lk // 2) % 4).astype(np.int64)
sort_idx = np.argsort(local_shard, kind="stable")
counts = np.bincount(local_shard, minlength=4)
M = 4096
assert counts.max() <= M
k = np.full((4, M), 0xFFFFFFFF, np.uint32)
a = np.zeros((4, M), np.bool_)
s2 = np.zeros((4, M), np.float32)
scatter = np.full((4, M), -1, np.int64)
starts = np.zeros(5, np.int64)
np.cumsum(counts, out=starts[1:])
rows = local_shard[sort_idx]
cols = np.arange(n_local) - starts[rows]
k[rows, cols] = lk[sort_idx]
a[rows, cols] = la[sort_idx]
s2[rows, cols] = ls[sort_idx]
scatter[rows, cols] = sort_idx

mesh = make_mesh()  # global: 8 devices across both processes
spec = NamedSharding(mesh, P(REPLAY_AXIS, None))
gk = jax.make_array_from_process_local_data(spec, k)
ga = jax.make_array_from_process_local_data(spec, a)
gs = jax.make_array_from_process_local_data(spec, s2)
fn = build_sharded_replay_fn(mesh)
live, tomb, num_live, live_bytes = fn(gk, ga, gs)

# global reference (identical in both processes)
live_h, tomb_h = python_replay_reference(
    [(int(x), 0) for x in key], ver, np.zeros(n, np.int32), add)
# the psum crossed the process boundary: both processes see the GLOBAL count
assert int(num_live) == int(live_h.sum()), (int(num_live), int(live_h.sum()))

# my rows' masks from my addressable shards
shards = sorted(live.addressable_shards, key=lambda s: s.index[0].start)
live_local = np.concatenate([np.asarray(s.data) for s in shards])
my_live = np.zeros(n_local, bool)
sel = scatter.ravel() >= 0
my_live[scatter.ravel()[sel]] = live_local.ravel()[sel]
expected = live_h[mine]
assert np.array_equal(my_live, expected), "local winner masks disagree"
print(f"MP_OK pid={pid} num_live={int(num_live)} rows={n_local}", flush=True)
"""


def test_two_process_distributed_replay(tmp_path):
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    env = dict(os.environ)
    # strip the single-chip tunnel sitecustomize; the workers set their
    # own platform env before importing jax
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.pop("JAX_PLATFORMS", None)
    env.pop("XLA_FLAGS", None)
    pp = env.get("PYTHONPATH", "")
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in pp.split(os.pathsep) if "axon" not in p)

    script = tmp_path / "worker.py"
    script.write_text(WORKER.replace("{repo!r}", repr(REPO)))
    procs = [
        subprocess.Popen([sys.executable, str(script), str(pid), str(port)],
                         env=env, stdout=subprocess.PIPE,
                         stderr=subprocess.STDOUT, text=True)
        for pid in (0, 1)
    ]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=180)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append(out)
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {pid} failed:\n{out[-3000:]}"
        assert f"MP_OK pid={pid}" in out, out[-3000:]


WORKER_BLOCKWISE = r"""
import os, sys, time
pid = int(sys.argv[1])
port = sys.argv[2]
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
import jax
jax.distributed.initialize(
    coordinator_address=f"127.0.0.1:{port}", num_processes=2, process_id=pid)
import numpy as np
sys.path.insert(0, {repo!r})
from jax.sharding import NamedSharding, PartitionSpec as P
from delta_tpu.ops.replay import _unpack_bits, pad_bucket
from delta_tpu.parallel.mesh import REPLAY_AXIS, make_mesh
from delta_tpu.parallel.sharded_blockwise import _PAD_KEY, _step_fn

t0 = time.time()
assert len(jax.devices()) == 8 and len(jax.local_devices()) == 4

# deterministic GLOBAL history, identical in both processes: >=2M rows
# per process (VERDICT r4 ask #6 — the DCN-analogue path at scale)
rng = np.random.default_rng(7)
n = 4_000_000
K = 1_000_000
key = rng.integers(0, K, n).astype(np.uint32)
# rows are already in chronological (array) order; the winner per key
# is last-wins over that order

# routing: process = key % 2, local shard = (key // 2) % 4 (injective
# per key -> per-shard dedup is globally correct)
mine = key % 2 == pid
lk = key[mine]
n_local = int(mine.sum())
assert n_local >= 1_900_000, n_local
local_shard = ((lk // 2) % 4).astype(np.int64)

# GLOBAL block geometry (both processes must agree): max rows on any
# of the 8 global shards
g_shard = (key % 2) * 4 + ((key // 2) % 4)
g_counts = np.bincount(g_shard, minlength=8)
m = 1 << 17
n_blocks = -(-int(g_counts.max()) // m)
assert n_blocks > 1, n_blocks  # every shard streams multiple blocks
L = n_blocks * m

# local slab [4, L] in chronological order per shard
sort_idx = np.argsort(local_shard, kind="stable")
counts = np.bincount(local_shard, minlength=4)
starts = np.zeros(5, np.int64)
np.cumsum(counts, out=starts[1:])
rows = local_shard[sort_idx]
cols = np.arange(n_local) - starts[rows]
local_key = (lk // 8).astype(np.uint32)  # dense per shard, < K/8
keys_slab = np.full((4, L), _PAD_KEY, np.uint32)
keys_slab[rows, cols] = local_key[sort_idx]
scatter = np.full((4, L), -1, np.int64)
scatter[rows, cols] = sort_idx

mesh = make_mesh()  # 8 devices across both processes
spec = NamedSharding(mesh, P(REPLAY_AXIS, None))
vec_spec = NamedSharding(mesh, P(REPLAY_AXIS))
n_words = pad_bucket(-(-(K // 8 + 1) // 32), min_bucket=256)
seen = jax.make_array_from_process_local_data(
    spec, np.zeros((4, n_words), np.uint32))
step = _step_fn(mesh, m)

winner = np.zeros(n_local, bool)
for b in reversed(range(n_blocks)):
    blk = np.ascontiguousarray(keys_slab[:, b * m:(b + 1) * m])
    n_real = np.clip(counts - b * m, 0, m).astype(np.int32)
    gblk = jax.make_array_from_process_local_data(spec, blk)
    greal = jax.make_array_from_process_local_data(vec_spec, n_real)
    seen, packed = step(seen, gblk, greal)
    shards = sorted(packed.addressable_shards,
                    key=lambda s: s.index[0].start)
    words = np.stack([np.asarray(s.data).reshape(-1) for s in shards])
    tgt = scatter[:, b * m:(b + 1) * m]
    for s in range(4):
        w = _unpack_bits(words[s], m)
        sel = tgt[s] >= 0
        winner[tgt[s][sel]] = w[sel]

# vectorized global oracle (lexsort last-wins), then my rows
shift = np.uint64(max(1, int(n - 1).bit_length()))
k64 = (key.astype(np.uint64) << shift) | np.arange(n, dtype=np.uint64)
srt = np.sort(k64)
kk = srt >> shift
boundary = np.empty(n, bool)
boundary[:-1] = kk[:-1] != kk[1:]
boundary[-1] = True
idx = (srt & np.uint64((1 << int(shift)) - 1))[boundary].astype(np.int64)
winner_h = np.zeros(n, bool)
winner_h[idx] = True
expected = winner_h[mine]
assert np.array_equal(winner, expected), "blockwise winner masks disagree"
blocks_per_shard = np.maximum(-(-counts // m), 0)
assert (blocks_per_shard > 1).all(), blocks_per_shard
print(f"MPBW_OK pid={{pid}} rows={{n_local}} blocks={{blocks_per_shard.tolist()}} "
      f"wall={{time.time() - t0:.1f}}s", flush=True)
"""


def test_two_process_blockwise_replay_4m(tmp_path):
    """Sharded x blockwise at scale across a REAL process boundary:
    >=2M rows per process on one 8-device global mesh, every shard
    streaming >1 bounded block with a persistent device bitset, winner
    masks parity vs the global vectorized oracle (VERDICT r4 ask #6)."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.pop("JAX_PLATFORMS", None)
    env.pop("XLA_FLAGS", None)
    pp = env.get("PYTHONPATH", "")
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in pp.split(os.pathsep) if "axon" not in p)

    script = tmp_path / "worker_bw.py"
    script.write_text(
        WORKER_BLOCKWISE.replace("{repo!r}", repr(REPO))
        .replace("{{", "\x00").replace("}}", "\x01")
        .replace("\x00", "{").replace("\x01", "}"))
    procs = [
        subprocess.Popen([sys.executable, str(script), str(pid), str(port)],
                         env=env, stdout=subprocess.PIPE,
                         stderr=subprocess.STDOUT, text=True)
        for pid in (0, 1)
    ]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=300)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append(out)
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {pid} failed:\n{out[-3000:]}"
        assert f"MPBW_OK pid={pid}" in out, out[-3000:]

"""REORG PURGE, DROP FEATURE pre-downgrade flows, and the
OPTIMIZE-with-DVs regression (rewrites must not resurrect soft-deleted
rows)."""

import numpy as np
import pyarrow as pa
import pytest

import delta_tpu.api as dta
from delta_tpu.commands.dml import delete
from delta_tpu.commands.dropfeature import drop_feature
from delta_tpu.commands.reorg import reorg_purge
from delta_tpu.errors import DeltaError
from delta_tpu.expressions.parser import parse_expression
from delta_tpu.sql import sql
from delta_tpu.table import Table


def _dv_table(path, n=100):
    data = pa.table({
        "id": pa.array(np.arange(n, dtype=np.int64)),
        "v": pa.array(np.arange(n, dtype=np.float64)),
    })
    dta.write_table(path, data, mode="append",
                    properties={"delta.enableDeletionVectors": "true"})
    delete(Table.for_path(path), parse_expression("id < 10"))
    return Table.for_path(path)


def test_reorg_purge_materializes_dv_deletes(tmp_table_path):
    t = _dv_table(tmp_table_path)
    files = t.latest_snapshot().scan().files()
    assert any(f.deletionVector is not None for f in files)

    metrics = reorg_purge(t)
    assert metrics.num_files_removed >= 1

    snap = t.latest_snapshot()
    assert all(f.deletionVector is None for f in snap.scan().files())
    rows = dta.read_table(tmp_table_path)
    assert sorted(rows.column("id").to_pylist()) == list(range(10, 100))


def test_reorg_purge_noop_without_dvs(tmp_table_path):
    dta.write_table(tmp_table_path,
                    pa.table({"id": pa.array([1, 2], pa.int64())}),
                    mode="append")
    before = Table.for_path(tmp_table_path).latest_snapshot().version
    metrics = reorg_purge(Table.for_path(tmp_table_path))
    assert metrics.num_files_removed == 0
    assert Table.for_path(tmp_table_path).latest_snapshot().version == before


def test_optimize_does_not_resurrect_dv_deleted_rows(tmp_table_path):
    """Regression: OPTIMIZE reads must apply deletion vectors before
    rewriting a bin."""
    t = _dv_table(tmp_table_path)
    # add more small files so compaction has a bin to work on
    for start in (100, 200):
        dta.write_table(
            tmp_table_path,
            pa.table({"id": pa.array(np.arange(start, start + 50, dtype=np.int64)),
                      "v": pa.array(np.zeros(50))}),
            mode="append")
    metrics = t.optimize().execute_compaction()
    assert metrics.num_files_removed >= 2
    rows = dta.read_table(tmp_table_path)
    ids = sorted(rows.column("id").to_pylist())
    assert ids == list(range(10, 100)) + list(range(100, 150)) + list(range(200, 250))
    # DVs were purged by the rewrite
    assert all(f.deletionVector is None
               for f in Table.for_path(tmp_table_path).latest_snapshot().scan().files())


def test_drop_feature_deletion_vectors(tmp_table_path):
    t = _dv_table(tmp_table_path)
    # reader-writer feature requires TRUNCATE HISTORY
    with pytest.raises(DeltaError, match="TRUNCATE HISTORY"):
        drop_feature(t, "deletionVectors")
    v = drop_feature(t, "deletionVectors", truncate_history=True)
    snap = Table.for_path(tmp_table_path).latest_snapshot()
    assert snap.version == v
    assert "deletionVectors" not in (snap.protocol.writerFeatures or [])
    assert "deletionVectors" not in (snap.protocol.readerFeatures or [])
    assert "delta.enableDeletionVectors" not in snap.metadata.configuration
    rows = dta.read_table(tmp_table_path)
    assert sorted(rows.column("id").to_pylist()) == list(range(10, 100))
    # history was truncated: old commits are gone but head still loads
    assert Table.for_path(tmp_table_path).latest_snapshot().version == v


def test_drop_feature_ict(tmp_table_path):
    dta.write_table(tmp_table_path,
                    pa.table({"id": pa.array([1], pa.int64())}),
                    mode="append",
                    properties={"delta.enableInCommitTimestamps": "true"})
    t = Table.for_path(tmp_table_path)
    assert "inCommitTimestamp" in (t.latest_snapshot().protocol.writerFeatures or [])
    v = drop_feature(t, "inCommitTimestamp")
    snap = Table.for_path(tmp_table_path).latest_snapshot()
    assert "inCommitTimestamp" not in (snap.protocol.writerFeatures or [])
    conf = snap.metadata.configuration
    assert "delta.enableInCommitTimestamps" not in conf
    assert "delta.inCommitTimestampEnablementVersion" not in conf


def test_add_constraint_upgrades_legacy_protocol(tmp_table_path):
    """CHECK constraints demand writer v3 (PROTOCOL.md legacy table)."""
    dta.write_table(tmp_table_path,
                    pa.table({"id": pa.array([1, 2], pa.int64())}),
                    mode="append")
    assert Table.for_path(tmp_table_path).latest_snapshot().protocol.minWriterVersion == 2
    sql(f"ALTER TABLE '{tmp_table_path}' ADD CONSTRAINT pos CHECK (id > 0)")
    proto = Table.for_path(tmp_table_path).latest_snapshot().protocol
    assert proto.minWriterVersion == 3
    assert proto.writerFeatures is None  # legacy bump, not feature vectors


def test_drop_feature_check_constraints_blocked(tmp_table_path):
    # ICT forces a writer-7 feature-vector protocol, so the later
    # ADD CONSTRAINT lists checkConstraints explicitly
    dta.write_table(tmp_table_path,
                    pa.table({"id": pa.array([1, 2], pa.int64())}),
                    mode="append",
                    properties={"delta.enableInCommitTimestamps": "true"})
    sql(f"ALTER TABLE '{tmp_table_path}' ADD CONSTRAINT pos CHECK (id > 0)")
    t = Table.for_path(tmp_table_path)
    assert "checkConstraints" in (t.latest_snapshot().protocol.writerFeatures or [])
    with pytest.raises(DeltaError, match="DROP CONSTRAINT"):
        drop_feature(t, "checkConstraints")
    sql(f"ALTER TABLE '{tmp_table_path}' DROP CONSTRAINT pos")
    drop_feature(Table.for_path(tmp_table_path), "checkConstraints")
    proto = Table.for_path(tmp_table_path).latest_snapshot().protocol
    assert "checkConstraints" not in (proto.writerFeatures or [])


def test_drop_feature_legacy_protocol_refused(tmp_table_path):
    dta.write_table(tmp_table_path,
                    pa.table({"id": pa.array([1, 2], pa.int64())}),
                    mode="append")
    sql(f"ALTER TABLE '{tmp_table_path}' ADD CONSTRAINT pos CHECK (id > 0)")
    with pytest.raises(DeltaError, match="listed explicitly"):
        drop_feature(Table.for_path(tmp_table_path), "checkConstraints")


def test_drop_feature_errors(tmp_table_path):
    dta.write_table(tmp_table_path,
                    pa.table({"id": pa.array([1], pa.int64())}),
                    mode="append")
    t = Table.for_path(tmp_table_path)
    with pytest.raises(DeltaError, match="unknown table feature"):
        drop_feature(t, "nosuchfeature")
    with pytest.raises(DeltaError, match="not present"):
        drop_feature(t, "deletionVectors")


def test_drop_feature_collapses_to_legacy_protocol(tmp_table_path):
    """After dropping the only non-legacy feature, the protocol shrinks
    back to legacy (reader, writer) versions."""
    dta.write_table(tmp_table_path,
                    pa.table({"id": pa.array([1], pa.int64())}),
                    mode="append",
                    properties={"delta.enableInCommitTimestamps": "true"})
    t = Table.for_path(tmp_table_path)
    drop_feature(t, "inCommitTimestamp")
    proto = Table.for_path(tmp_table_path).latest_snapshot().protocol
    assert proto.writerFeatures is None
    assert proto.minWriterVersion <= 2


def test_sql_drop_feature_and_reorg(tmp_table_path):
    t = _dv_table(tmp_table_path)
    metrics = sql(f"REORG TABLE '{tmp_table_path}' APPLY (PURGE)")
    assert metrics.num_files_removed >= 1
    sql(f"ALTER TABLE '{tmp_table_path}' DROP FEATURE deletionVectors "
        "TRUNCATE HISTORY")
    proto = Table.for_path(tmp_table_path).latest_snapshot().protocol
    assert "deletionVectors" not in (proto.writerFeatures or [])


def test_sql_alter_add_rename_drop_columns(tmp_table_path):
    dta.write_table(tmp_table_path,
                    pa.table({"id": pa.array([1, 2], pa.int64()),
                              "v": pa.array([1.0, 2.0])}),
                    mode="append")
    sql(f"ALTER TABLE '{tmp_table_path}' ADD COLUMNS (note string)")
    snap = Table.for_path(tmp_table_path).latest_snapshot()
    assert [f.name for f in snap.schema.fields] == ["id", "v", "note"]

    sql(f"ALTER TABLE '{tmp_table_path}' SET TBLPROPERTIES "
        "('delta.columnMapping.mode' = 'name')")
    sql(f"ALTER TABLE '{tmp_table_path}' RENAME COLUMN note TO comment")
    snap = Table.for_path(tmp_table_path).latest_snapshot()
    assert [f.name for f in snap.schema.fields] == ["id", "v", "comment"]

    sql(f"ALTER TABLE '{tmp_table_path}' DROP COLUMN comment")
    snap = Table.for_path(tmp_table_path).latest_snapshot()
    assert [f.name for f in snap.schema.fields] == ["id", "v"]
    rows = dta.read_table(tmp_table_path)
    assert sorted(rows.column("id").to_pylist()) == [1, 2]

    sql(f"ALTER TABLE '{tmp_table_path}' ADD COLUMNS (cnt int)")
    sql(f"ALTER TABLE '{tmp_table_path}' SET TBLPROPERTIES "
        "('delta.enableTypeWidening' = 'true')")
    sql(f"ALTER TABLE '{tmp_table_path}' ALTER COLUMN cnt TYPE long")
    snap = Table.for_path(tmp_table_path).latest_snapshot()
    assert snap.schema["cnt"].dataType.name == "long"

    # without IF EXISTS, unsetting an unknown key is an error
    import pytest as _pytest

    from delta_tpu.errors import DeltaError

    with _pytest.raises(DeltaError, match="non-existent"):
        sql(f"ALTER TABLE '{tmp_table_path}' UNSET TBLPROPERTIES ('nokey')")
    sql(f"ALTER TABLE '{tmp_table_path}' UNSET TBLPROPERTIES IF EXISTS "
        "('nokey')")


def test_upgrade_to_feature_vectors_keeps_implied_legacy_features(tmp_table_path):
    """Enabling a non-legacy feature on a legacy protocol must fold the
    implicitly supported legacy features into the new feature lists."""
    from delta_tpu.features import COLUMN_MAPPING, is_feature_supported

    dta.write_table(tmp_table_path,
                    pa.table({"id": pa.array([1], pa.int64())}),
                    mode="append",
                    properties={"delta.columnMapping.mode": "name"})
    proto = Table.for_path(tmp_table_path).latest_snapshot().protocol
    assert is_feature_supported(proto, COLUMN_MAPPING)
    # now activate a non-legacy feature → protocol moves to vectors
    sql(f"ALTER TABLE '{tmp_table_path}' SET TBLPROPERTIES "
        "('delta.enableDeletionVectors' = 'true')")
    proto = Table.for_path(tmp_table_path).latest_snapshot().protocol
    assert proto.minWriterVersion == 7
    assert "columnMapping" in (proto.writerFeatures or [])
    assert "columnMapping" in (proto.readerFeatures or [])
    assert is_feature_supported(proto, COLUMN_MAPPING)


def test_add_column_with_default_upgrades_protocol(tmp_table_path):
    """ADD COLUMNS carrying CURRENT_DEFAULT metadata must list the
    allowColumnDefaults writer feature."""
    from delta_tpu.colgen import default_field
    from delta_tpu.commands.alter import add_columns
    from delta_tpu.models.schema import STRING

    dta.write_table(tmp_table_path,
                    pa.table({"id": pa.array([1], pa.int64())}),
                    mode="append")
    add_columns(Table.for_path(tmp_table_path),
                [default_field("status", STRING, "'new'")])
    proto = Table.for_path(tmp_table_path).latest_snapshot().protocol
    assert proto.minWriterVersion == 7
    assert "allowColumnDefaults" in (proto.writerFeatures or [])
    dta.write_table(tmp_table_path,
                    pa.table({"id": pa.array([2], pa.int64())}),
                    mode="append")
    rows = dta.read_table(tmp_table_path)
    assert set(rows.column("status").to_pylist()) <= {None, "new"}


def test_sql_bad_type_raises_delta_error(tmp_table_path):
    dta.write_table(tmp_table_path,
                    pa.table({"id": pa.array([1], pa.int64())}),
                    mode="append")
    with pytest.raises(DeltaError, match="unknown primitive type"):
        sql(f"ALTER TABLE '{tmp_table_path}' ALTER COLUMN id TYPE frobtype")


def test_dml_on_column_mapped_table(tmp_table_path):
    """Copy-on-write DELETE and UPDATE work after a rename under column
    mapping (physical names differ from logical)."""
    dta.write_table(tmp_table_path,
                    pa.table({"id": pa.array(np.arange(10, dtype=np.int64)),
                              "v": pa.array(np.arange(10, dtype=np.float64))}),
                    mode="append")
    sql(f"ALTER TABLE '{tmp_table_path}' SET TBLPROPERTIES "
        "('delta.columnMapping.mode' = 'name')")
    sql(f"ALTER TABLE '{tmp_table_path}' RENAME COLUMN v TO val")
    sql(f"DELETE FROM '{tmp_table_path}' WHERE id < 3")
    sql(f"UPDATE '{tmp_table_path}' SET val = 99.0 WHERE id = 5")
    rows = dta.read_table(tmp_table_path)
    assert sorted(rows.column("id").to_pylist()) == list(range(3, 10))
    by_id = dict(zip(rows.column("id").to_pylist(),
                     rows.column("val").to_pylist()))
    assert by_id[5] == 99.0
    # OPTIMIZE under mapping also works
    Table.for_path(tmp_table_path).optimize().execute_compaction()
    rows = dta.read_table(tmp_table_path)
    assert sorted(rows.column("id").to_pylist()) == list(range(3, 10))


def test_reorg_upgrade_uniform(tmp_table_path):
    """REORG ... APPLY (UPGRADE UNIFORM): DV purge + feature drop +
    compat/UniForm enablement in one command."""
    import numpy as np
    import pyarrow as pa

    import delta_tpu.api as dta
    from delta_tpu.commands.dml import delete
    from delta_tpu.expressions import col, lit
    from delta_tpu.sql import sql
    from delta_tpu.table import Table

    dta.write_table(tmp_table_path, pa.table(
        {"id": pa.array(np.arange(10, dtype=np.int64))}),
        properties={"delta.enableDeletionVectors": "true"})
    delete(Table.for_path(tmp_table_path), col("id") < lit(3))
    sql(f"REORG TABLE '{tmp_table_path}' APPLY "
        "(UPGRADE UNIFORM (ICEBERG_COMPAT_VERSION = 2))")
    snap = Table.for_path(tmp_table_path).latest_snapshot()
    conf = snap.metadata.configuration
    assert conf.get("delta.enableIcebergCompatV2") == "true"
    assert conf.get("delta.columnMapping.mode") == "name"
    assert "iceberg" in conf.get("delta.universalFormat.enabledFormats", "")
    # the DV FEATURE may remain in the protocol (reference semantics);
    # what matters is the config is off and no live file carries a DV
    assert conf.get("delta.enableDeletionVectors") == "false"
    # no DVs survive, reads still correct through the new mapping
    assert not any(
        d for d in
        snap.state.add_files_table.column("deletion_vector").to_pylist())
    assert sorted(dta.read_table(tmp_table_path).column("id").to_pylist()) \
        == list(range(3, 10))
    # and subsequent compat-validated commits pass
    dta.write_table(tmp_table_path, pa.table(
        {"id": pa.array([100], pa.int64())}), mode="append")

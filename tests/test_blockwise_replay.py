"""Blockwise (>HBM) replay: bounded-memory streaming equals the one-shot
kernel and the sequential reference."""

import numpy as np
import pytest

from delta_tpu.ops.replay import python_replay_reference, replay_select
from delta_tpu.ops.replay_blockwise import replay_select_blockwise
from delta_tpu.utils.synth import fa_history


@pytest.mark.parametrize("n,block", [
    (10_000, 2048),      # many small blocks
    (300_000, 65_536),   # several large blocks
    (5_000, 1 << 22),    # single block (degenerate)
])
def test_blockwise_matches_reference(n, block):
    pk, dk, ver, order, add, _ = fa_history(n, seed=n, dv_frac=0.02)
    live_b, tomb_b = replay_select_blockwise(
        [pk, dk], ver, order, add, block_rows=block)
    live_h, tomb_h = python_replay_reference(
        list(zip(pk.tolist(), dk.tolist())), ver, order, add)
    np.testing.assert_array_equal(live_b, live_h)
    np.testing.assert_array_equal(tomb_b, tomb_h)


def test_blockwise_matches_one_shot_kernel():
    pk, dk, ver, order, add, _ = fa_history(200_000, seed=3, dv_frac=0.01)
    live_b, tomb_b = replay_select_blockwise(
        [pk, dk], ver, order, add, block_rows=32_768)
    live_1, tomb_1 = replay_select([pk, dk], ver, order, add)
    np.testing.assert_array_equal(live_b, live_1)
    np.testing.assert_array_equal(tomb_b, tomb_1)


def test_blockwise_out_of_order_rows():
    rng = np.random.default_rng(5)
    n = 50_000
    pk = rng.integers(0, 9000, n).astype(np.uint32)
    dk = rng.integers(0, 2, n).astype(np.uint32)
    ver = rng.integers(0, 512, n).astype(np.int32)   # NOT sorted
    order = rng.integers(0, 64, n).astype(np.int32)
    add = rng.random(n) < 0.6
    live_b, tomb_b = replay_select_blockwise(
        [pk, dk], ver, order, add, block_rows=8192)
    live_h, tomb_h = python_replay_reference(
        list(zip(pk.tolist(), dk.tolist())), ver, order, add)
    np.testing.assert_array_equal(live_b, live_h)
    np.testing.assert_array_equal(tomb_b, tomb_h)


def test_blockwise_device_footprint_is_bounded():
    """The device never holds more than one block + the key bitset: the
    jitted block kernel's operand shapes depend on block_rows, not n."""
    from delta_tpu.ops.replay import pad_bucket

    n, block = 300_000, 16_384
    m = pad_bucket(block)
    assert m * 4 + m // 8 < n  # block footprint well under total rows
    pk, dk, ver, order, add, _ = fa_history(n, seed=9)
    live_b, _ = replay_select_blockwise(
        [pk, dk], ver, order, add, block_rows=block)
    assert live_b.sum() > 0


def test_product_load_routes_blockwise_above_threshold(
        tmp_table_path, monkeypatch):
    """A snapshot load whose action count crosses BLOCKWISE_MIN_ROWS
    reconstructs through the streaming path, with identical results."""
    import pyarrow as pa

    import delta_tpu.api as dta
    import delta_tpu.replay.state as state_mod
    from delta_tpu.engine.tpu import TpuEngine
    from delta_tpu.table import Table

    dta.write_table(tmp_table_path, pa.table(
        {"id": pa.array(np.arange(1000, dtype=np.int64))}),
        target_rows_per_file=100)
    for i in range(3):
        dta.write_table(tmp_table_path, pa.table(
            {"id": pa.array([i], pa.int64())}), mode="append")

    normal = Table.for_path(tmp_table_path, TpuEngine()).latest_snapshot()
    monkeypatch.setattr(state_mod, "BLOCKWISE_MIN_ROWS", 1)
    blockwise = Table.for_path(
        tmp_table_path, TpuEngine()).latest_snapshot()
    a = sorted(normal.state.add_files_table.column("path").to_pylist())
    b = sorted(blockwise.state.add_files_table.column("path").to_pylist())
    assert a == b
    assert normal.state.size_in_bytes == blockwise.state.size_in_bytes

"""Blockwise (>HBM) replay: bounded-memory streaming equals the one-shot
kernel and the sequential reference."""

import numpy as np
import pytest

from delta_tpu.ops.replay import python_replay_reference, replay_select
from delta_tpu.ops.replay_blockwise import replay_select_blockwise
from delta_tpu.utils.synth import fa_history


@pytest.mark.parametrize("n,block", [
    (10_000, 2048),      # many small blocks
    (300_000, 65_536),   # several large blocks
    (5_000, 1 << 22),    # single block (degenerate)
])
def test_blockwise_matches_reference(n, block):
    pk, dk, ver, order, add, _ = fa_history(n, seed=n, dv_frac=0.02)
    live_b, tomb_b = replay_select_blockwise(
        [pk, dk], ver, order, add, block_rows=block)
    live_h, tomb_h = python_replay_reference(
        list(zip(pk.tolist(), dk.tolist())), ver, order, add)
    np.testing.assert_array_equal(live_b, live_h)
    np.testing.assert_array_equal(tomb_b, tomb_h)


def test_blockwise_matches_one_shot_kernel():
    pk, dk, ver, order, add, _ = fa_history(200_000, seed=3, dv_frac=0.01)
    live_b, tomb_b = replay_select_blockwise(
        [pk, dk], ver, order, add, block_rows=32_768)
    live_1, tomb_1 = replay_select([pk, dk], ver, order, add)
    np.testing.assert_array_equal(live_b, live_1)
    np.testing.assert_array_equal(tomb_b, tomb_1)


def test_blockwise_out_of_order_rows():
    rng = np.random.default_rng(5)
    n = 50_000
    pk = rng.integers(0, 9000, n).astype(np.uint32)
    dk = rng.integers(0, 2, n).astype(np.uint32)
    ver = rng.integers(0, 512, n).astype(np.int32)   # NOT sorted
    order = rng.integers(0, 64, n).astype(np.int32)
    add = rng.random(n) < 0.6
    live_b, tomb_b = replay_select_blockwise(
        [pk, dk], ver, order, add, block_rows=8192)
    live_h, tomb_h = python_replay_reference(
        list(zip(pk.tolist(), dk.tolist())), ver, order, add)
    np.testing.assert_array_equal(live_b, live_h)
    np.testing.assert_array_equal(tomb_b, tomb_h)


def test_blockwise_device_footprint_is_bounded():
    """The device never holds more than one block + the key bitset: the
    jitted block kernel's operand shapes depend on block_rows, not n."""
    from delta_tpu.ops.replay import pad_bucket

    n, block = 300_000, 16_384
    m = pad_bucket(block)
    assert m * 4 + m // 8 < n  # block footprint well under total rows
    pk, dk, ver, order, add, _ = fa_history(n, seed=9)
    live_b, _ = replay_select_blockwise(
        [pk, dk], ver, order, add, block_rows=block)
    assert live_b.sum() > 0

"""Multi-device sharded replay over the virtual 8-device CPU mesh."""

import jax
import numpy as np
import pytest

from delta_tpu.ops.replay import python_replay_reference
from delta_tpu.parallel import make_mesh, sharded_replay_select
from delta_tpu.parallel.sharded_replay import build_sharded_replay_fn, route_to_shards


def _history(rng, n, n_keys, n_versions):
    pk = rng.integers(0, n_keys, n).astype(np.uint32)
    dk = rng.integers(0, 2, n).astype(np.uint32)
    ver = np.sort(rng.integers(0, n_versions, n)).astype(np.int32)
    order = np.zeros(n, np.int32)
    for v in np.unique(ver):
        s = ver == v
        order[s] = np.arange(s.sum())
    add = rng.random(n) < 0.6
    size = rng.integers(100, 10_000, n).astype(np.int64)
    return pk, dk, ver, order, add, size


def test_mesh_has_8_devices():
    assert len(jax.devices()) == 8
    mesh = make_mesh()
    assert mesh.devices.size == 8


@pytest.mark.parametrize("n", [10, 1000, 30_000])
def test_sharded_matches_reference(n):
    rng = np.random.default_rng(n)
    pk, dk, ver, order, add, size = _history(rng, n, max(2, n // 4), max(2, n // 8))
    mesh = make_mesh()
    live, tomb, num_live, _ = sharded_replay_select(pk, dk, ver, order, add, size, mesh)
    live_h, tomb_h = python_replay_reference(
        list(zip(pk.tolist(), dk.tolist())), ver, order, add
    )
    np.testing.assert_array_equal(live, live_h)
    np.testing.assert_array_equal(tomb, tomb_h)
    assert num_live == int(live_h.sum())


def test_sharded_on_subset_mesh():
    rng = np.random.default_rng(3)
    pk, dk, ver, order, add, size = _history(rng, 5000, 700, 50)
    for nd in (1, 2, 4):
        mesh = make_mesh(n_devices=nd)
        live, tomb, num_live, _ = sharded_replay_select(pk, dk, ver, order, add, size, mesh)
        live_h, _ = python_replay_reference(
            list(zip(pk.tolist(), dk.tolist())), ver, order, add
        )
        np.testing.assert_array_equal(live, live_h)


def test_routing_is_key_complete():
    """Every row lands in exactly one shard; all rows of a key share it."""
    rng = np.random.default_rng(5)
    pk, dk, ver, order, add, size = _history(rng, 2000, 97, 20)
    ops, scatter = route_to_shards(pk, dk, ver, order, add, size, 8)
    flat = scatter.ravel()
    placed = np.sort(flat[flat >= 0])
    np.testing.assert_array_equal(placed, np.arange(len(pk)))
    k0 = ops[0]
    for s in range(8):
        keys_here = k0[s][k0[s] != 0xFFFFFFFF]
        assert np.all(keys_here % 8 == s)


def test_sharded_out_of_order_rows():
    """Non-chronological input exercises the host lexsort pre-pass."""
    rng = np.random.default_rng(23)
    n = 4000
    pk = rng.integers(0, 600, n).astype(np.uint32)
    dk = rng.integers(0, 2, n).astype(np.uint32)
    ver = rng.integers(0, 64, n).astype(np.int32)  # NOT sorted
    order = rng.integers(0, 32, n).astype(np.int32)
    add = rng.random(n) < 0.6
    size = rng.integers(100, 10_000, n).astype(np.int64)
    mesh = make_mesh()
    live, tomb, num_live, _ = sharded_replay_select(pk, dk, ver, order, add, size, mesh)
    live_h, tomb_h = python_replay_reference(
        list(zip(pk.tolist(), dk.tolist())), ver, order, add
    )
    np.testing.assert_array_equal(live, live_h)
    np.testing.assert_array_equal(tomb, tomb_h)
    assert num_live == int(live_h.sum())


def test_step_fn_compiles_with_shardings():
    """The jitted sharded step lowers and runs with explicit NamedSharding
    inputs (what dryrun_multichip exercises)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = make_mesh()
    fn = build_sharded_replay_fn(mesh)
    rng = np.random.default_rng(11)
    pk, dk, ver, order, add, size = _history(rng, 4000, 300, 16)
    ops, _ = route_to_shards(pk, dk, ver, order, add, size, 8)
    spec = NamedSharding(mesh, P("shard", None))
    device_ops = tuple(jax.device_put(o, spec) for o in ops)
    live, tomb, num_live, live_bytes = fn(*device_ops)
    assert live.shape == ops[0].shape
    assert int(num_live) > 0


def _fa_history(rng, n, n_versions, dv_frac=0.0):
    """First-appearance-coded history — the shared scanner-shaped
    generator (delta_tpu.utils.synth), seeded from `rng`."""
    from delta_tpu.utils.synth import fa_history

    return fa_history(n, seed=int(rng.integers(0, 2**31)),
                      dv_frac=dv_frac, n_versions=n_versions)


@pytest.mark.parametrize("dv_frac", [0.0, 0.05])
def test_sharded_fa_path_matches_reference(dv_frac):
    """The delta-coded sharded route (flags + refs + sparse DV lane)
    must agree with the sequential reference, including aggregates."""
    from delta_tpu.parallel.sharded_replay import (
        derive_fa_flags,
        route_to_shards_fa,
    )

    rng = np.random.default_rng(42)
    pk, dk, ver, order, add, size = _fa_history(rng, 20_000, 64, dv_frac)
    is_new = derive_fa_flags(pk)
    assert is_new is not None  # the stream IS first-appearance coded
    fa = route_to_shards_fa(pk, dk, is_new, add, 8)
    assert fa is not None
    # transfer economics: delta coding ships less than raw u32+bool+f32
    raw_bytes = fa.m * 8 * (4 + 1 + 4)
    assert fa.nbytes < raw_bytes

    mesh = make_mesh()
    live, tomb, num_live, live_bytes = sharded_replay_select(
        pk, dk, ver, order, add, size, mesh)
    live_h, tomb_h = python_replay_reference(
        list(zip(pk.tolist(), dk.tolist())), ver, order, add)
    np.testing.assert_array_equal(live, live_h)
    np.testing.assert_array_equal(tomb, tomb_h)
    assert num_live == int(live_h.sum())
    assert live_bytes == int(size[live_h].sum())


def test_sharded_fa_without_sizes_aggregates_on_host():
    rng = np.random.default_rng(7)
    pk, dk, ver, order, add, size = _fa_history(rng, 5_000, 32)
    mesh = make_mesh()
    live, tomb, num_live, live_bytes = sharded_replay_select(
        pk, dk, ver, order, add, None, mesh)
    live_h, _ = python_replay_reference(
        list(zip(pk.tolist(), dk.tolist())), ver, order, add)
    np.testing.assert_array_equal(live, live_h)
    assert num_live == int(live_h.sum())
    assert live_bytes == 0  # no size lane shipped


def test_sharded_fa_hint_is_used():
    """A scanner-style fa_hint (flags precomputed) routes through the
    delta-coded path without re-deriving flags."""
    rng = np.random.default_rng(9)
    pk, dk, ver, order, add, size = _fa_history(rng, 8_000, 32)
    from delta_tpu.parallel.sharded_replay import derive_fa_flags

    flags = derive_fa_flags(pk)
    mesh = make_mesh()
    live, tomb, num_live, _ = sharded_replay_select(
        pk, dk, ver, order, add, size, mesh,
        fa_hint=(flags, None, int(pk.max()) + 1))
    live_h, _ = python_replay_reference(
        list(zip(pk.tolist(), dk.tolist())), ver, order, add)
    np.testing.assert_array_equal(live, live_h)


def test_sharded_transfer_bytes_close_to_single_chip():
    """VERDICT round-1 item 5 'done' criterion: the sharded route's
    H2D bytes/row stay within 2x of the single-chip FA encoding."""
    from delta_tpu.ops.replay import _try_fa_encode, pad_bucket
    from delta_tpu.parallel.sharded_replay import (
        derive_fa_flags,
        route_to_shards_fa,
    )

    rng = np.random.default_rng(1)
    n = 1_000_000
    pk, dk, ver, order, add, size = _fa_history(rng, n, 10_000, 0.01)
    single = _try_fa_encode([pk, dk], n, pad_bucket(n))
    assert single is not None
    flags = derive_fa_flags(pk)
    sharded = route_to_shards_fa(pk, dk, flags, add, 8)
    assert sharded is not None
    # add_words ship in both cases; compare total H2D payloads
    single_total = single.nbytes + pad_bucket(n) // 8
    assert sharded.nbytes <= 2 * single_total, (
        sharded.nbytes, single_total)

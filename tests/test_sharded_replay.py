"""Multi-device sharded replay over the virtual 8-device CPU mesh."""

import jax
import numpy as np
import pytest

from delta_tpu.ops.replay import python_replay_reference
from delta_tpu.parallel import make_mesh, sharded_replay_select
from delta_tpu.parallel.sharded_replay import build_sharded_replay_fn, route_to_shards

# the fast CPU-only sharded lane: `pytest -m sharded8` runs exactly the
# in-process 8-emulated-device coverage (conftest forces the device count)
pytestmark = pytest.mark.sharded8


def _history(rng, n, n_keys, n_versions):
    pk = rng.integers(0, n_keys, n).astype(np.uint32)
    dk = rng.integers(0, 2, n).astype(np.uint32)
    ver = np.sort(rng.integers(0, n_versions, n)).astype(np.int32)
    order = np.zeros(n, np.int32)
    for v in np.unique(ver):
        s = ver == v
        order[s] = np.arange(s.sum())
    add = rng.random(n) < 0.6
    size = rng.integers(100, 10_000, n).astype(np.int64)
    return pk, dk, ver, order, add, size


def test_mesh_has_8_devices():
    assert len(jax.devices()) == 8
    mesh = make_mesh()
    assert mesh.devices.size == 8


@pytest.mark.parametrize("n", [10, 1000, 30_000])
def test_sharded_matches_reference(n):
    rng = np.random.default_rng(n)
    pk, dk, ver, order, add, size = _history(rng, n, max(2, n // 4), max(2, n // 8))
    mesh = make_mesh()
    live, tomb, num_live, _ = sharded_replay_select(pk, dk, ver, order, add, size, mesh)
    live_h, tomb_h = python_replay_reference(
        list(zip(pk.tolist(), dk.tolist())), ver, order, add
    )
    np.testing.assert_array_equal(live, live_h)
    np.testing.assert_array_equal(tomb, tomb_h)
    assert num_live == int(live_h.sum())


def test_sharded_on_subset_mesh():
    rng = np.random.default_rng(3)
    pk, dk, ver, order, add, size = _history(rng, 5000, 700, 50)
    for nd in (1, 2, 4):
        mesh = make_mesh(n_devices=nd)
        live, tomb, num_live, _ = sharded_replay_select(pk, dk, ver, order, add, size, mesh)
        live_h, _ = python_replay_reference(
            list(zip(pk.tolist(), dk.tolist())), ver, order, add
        )
        np.testing.assert_array_equal(live, live_h)


def test_routing_is_key_complete():
    """Every row lands in exactly one shard; all rows of a key share it."""
    rng = np.random.default_rng(5)
    pk, dk, ver, order, add, size = _history(rng, 2000, 97, 20)
    ops, scatter = route_to_shards(pk, dk, ver, order, add, size, 8)
    flat = scatter.ravel()
    placed = np.sort(flat[flat >= 0])
    np.testing.assert_array_equal(placed, np.arange(len(pk)))
    k0 = ops[0]
    for s in range(8):
        keys_here = k0[s][k0[s] != 0xFFFFFFFF]
        assert np.all(keys_here % 8 == s)


def test_sharded_out_of_order_rows():
    """Non-chronological input exercises the host lexsort pre-pass."""
    rng = np.random.default_rng(23)
    n = 4000
    pk = rng.integers(0, 600, n).astype(np.uint32)
    dk = rng.integers(0, 2, n).astype(np.uint32)
    ver = rng.integers(0, 64, n).astype(np.int32)  # NOT sorted
    order = rng.integers(0, 32, n).astype(np.int32)
    add = rng.random(n) < 0.6
    size = rng.integers(100, 10_000, n).astype(np.int64)
    mesh = make_mesh()
    live, tomb, num_live, _ = sharded_replay_select(pk, dk, ver, order, add, size, mesh)
    live_h, tomb_h = python_replay_reference(
        list(zip(pk.tolist(), dk.tolist())), ver, order, add
    )
    np.testing.assert_array_equal(live, live_h)
    np.testing.assert_array_equal(tomb, tomb_h)
    assert num_live == int(live_h.sum())


def test_step_fn_compiles_with_shardings():
    """The jitted sharded step lowers and runs with explicit NamedSharding
    inputs (what dryrun_multichip exercises)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = make_mesh()
    fn = build_sharded_replay_fn(mesh)
    rng = np.random.default_rng(11)
    pk, dk, ver, order, add, size = _history(rng, 4000, 300, 16)
    ops, _ = route_to_shards(pk, dk, ver, order, add, size, 8)
    spec = NamedSharding(mesh, P("shard", None))
    device_ops = tuple(jax.device_put(o, spec) for o in ops)
    live, tomb, num_live, live_bytes = fn(*device_ops)
    assert live.shape == ops[0].shape
    assert int(num_live) > 0


def _fa_history(rng, n, n_versions, dv_frac=0.0):
    """First-appearance-coded history — the shared scanner-shaped
    generator (delta_tpu.utils.synth), seeded from `rng`."""
    from delta_tpu.utils.synth import fa_history

    return fa_history(n, seed=int(rng.integers(0, 2**31)),
                      dv_frac=dv_frac, n_versions=n_versions)


@pytest.mark.parametrize("dv_frac", [0.0, 0.05])
def test_sharded_fa_path_matches_reference(dv_frac):
    """The delta-coded sharded route (flags + refs + sparse DV lane)
    must agree with the sequential reference, including aggregates."""
    from delta_tpu.parallel.sharded_replay import (
        derive_fa_flags,
        route_to_shards_fa,
    )

    rng = np.random.default_rng(42)
    pk, dk, ver, order, add, size = _fa_history(rng, 20_000, 64, dv_frac)
    is_new = derive_fa_flags(pk)
    assert is_new is not None  # the stream IS first-appearance coded
    fa = route_to_shards_fa(pk, dk, is_new, add, 8)
    assert fa is not None
    # transfer economics: delta coding ships less than raw u32+bool+f32
    raw_bytes = fa.m * 8 * (4 + 1 + 4)
    assert fa.nbytes < raw_bytes

    mesh = make_mesh()
    live, tomb, num_live, live_bytes = sharded_replay_select(
        pk, dk, ver, order, add, size, mesh)
    live_h, tomb_h = python_replay_reference(
        list(zip(pk.tolist(), dk.tolist())), ver, order, add)
    np.testing.assert_array_equal(live, live_h)
    np.testing.assert_array_equal(tomb, tomb_h)
    assert num_live == int(live_h.sum())
    assert live_bytes == int(size[live_h].sum())


def test_sharded_fa_without_sizes_aggregates_on_host():
    rng = np.random.default_rng(7)
    pk, dk, ver, order, add, size = _fa_history(rng, 5_000, 32)
    mesh = make_mesh()
    live, tomb, num_live, live_bytes = sharded_replay_select(
        pk, dk, ver, order, add, None, mesh)
    live_h, _ = python_replay_reference(
        list(zip(pk.tolist(), dk.tolist())), ver, order, add)
    np.testing.assert_array_equal(live, live_h)
    assert num_live == int(live_h.sum())
    assert live_bytes == 0  # no size lane shipped


def test_sharded_fa_hint_is_used():
    """A scanner-style fa_hint (flags precomputed) routes through the
    delta-coded path without re-deriving flags."""
    rng = np.random.default_rng(9)
    pk, dk, ver, order, add, size = _fa_history(rng, 8_000, 32)
    from delta_tpu.parallel.sharded_replay import derive_fa_flags

    flags = derive_fa_flags(pk)
    mesh = make_mesh()
    live, tomb, num_live, _ = sharded_replay_select(
        pk, dk, ver, order, add, size, mesh,
        fa_hint=(flags, None, int(pk.max()) + 1))
    live_h, _ = python_replay_reference(
        list(zip(pk.tolist(), dk.tolist())), ver, order, add)
    np.testing.assert_array_equal(live, live_h)


def test_sharded_transfer_bytes_close_to_single_chip():
    """VERDICT round-1 item 5 'done' criterion: the sharded route's
    H2D bytes/row stay within 2x of the single-chip FA encoding."""
    from delta_tpu.ops.replay import _try_fa_encode, pad_bucket
    from delta_tpu.parallel.sharded_replay import (
        derive_fa_flags,
        route_to_shards_fa,
    )

    rng = np.random.default_rng(1)
    n = 1_000_000
    pk, dk, ver, order, add, size = _fa_history(rng, n, 10_000, 0.01)
    single = _try_fa_encode([pk, dk], n, pad_bucket(n))
    assert single is not None
    flags = derive_fa_flags(pk)
    sharded = route_to_shards_fa(pk, dk, flags, add, 8)
    assert sharded is not None
    # add_words ship in both cases; compare total H2D payloads
    single_total = single.nbytes + pad_bucket(n) // 8
    assert sharded.nbytes <= 2 * single_total, (
        sharded.nbytes, single_total)


# ---------------------------------------------------- digest parity matrix


def _mask_digest(live, tomb):
    import hashlib

    h = hashlib.sha256()
    h.update(np.packbits(np.asarray(live, bool)).tobytes())
    h.update(np.packbits(np.asarray(tomb, bool)).tobytes())
    return h.hexdigest()


def _matrix_stream(kind, rng, n):
    """One named workload for the parity matrix."""
    if kind == "fa":                       # product path: scanner FA codes
        return _fa_history(rng, n, 64)
    if kind == "dv_heavy":                 # (path, dv) composite keys
        return _fa_history(rng, n, 64, dv_frac=0.5)
    if kind == "hashed":                   # host-hashed lanes: not FA-coded
        pk = rng.integers(0, 1 << 32, n, dtype=np.uint64).astype(np.uint32)
        dk = rng.integers(0, 3, n).astype(np.uint32)
        ver = np.sort(rng.integers(0, 64, n)).astype(np.int32)
        order = np.zeros(n, np.int32)
        for v in np.unique(ver):
            s = ver == v
            order[s] = np.arange(s.sum())
        add = rng.random(n) < 0.6
        size = rng.integers(100, 10_000, n).astype(np.int64)
        return pk, dk, ver, order, add, size
    if kind == "permuted":                 # non-chronological rows
        pk, dk, ver, order, add, size = _fa_history(rng, n, 64)
        p = rng.permutation(n)
        return pk[p], dk[p], ver[p], order[p], add[p], size[p]
    raise AssertionError(kind)


# --------------------------------------------------------- device residency


def _tpu_table(tmp_path, n_commits, files_per_commit=20):
    from delta_tpu.engine.tpu import TpuEngine
    from delta_tpu.models.actions import AddFile, RemoveFile
    from delta_tpu.models.schema import INTEGER, StructField, StructType
    from delta_tpu.table import Table

    eng = TpuEngine(replay_shards=8)
    t = Table.for_path(str(tmp_path), eng)
    t.create_transaction_builder().with_schema(
        StructType([StructField("x", INTEGER)])).build().commit()
    for i in range(n_commits):
        txn = t.start_transaction()
        for j in range(files_per_commit):
            txn.add_file(AddFile(
                path=f"p{i}_{j}.parquet", partitionValues={}, size=100 + j,
                modificationTime=1000 + i, dataChange=True))
        if i > 0:
            txn.remove_file(RemoveFile(
                path=f"p{i - 1}_0.parquet", deletionTimestamp=2000 + i,
                dataChange=True))
        txn.commit()
    return t


def test_update_ships_only_delta_rows(tmp_path):
    """Device residency: after a sharded load, advancing the snapshot
    ships exactly the padded delta slots (8 bytes each per shard) over
    the link — never the resident base rows — and the advanced masks
    match a cold reload bit-for-bit."""
    from delta_tpu import obs
    from delta_tpu.engine.tpu import TpuEngine
    from delta_tpu.models.actions import AddFile
    from delta_tpu.table import Table

    # stay under delta.checkpointInterval (10): a checkpoint-based load
    # reconstructs from parquet + tail and correctly skips residency
    t = _tpu_table(tmp_path, 8)
    snap = t.latest_snapshot()
    _ = snap.state.live_mask  # force replay
    res = snap._state.resident
    assert res is not None, "sharded load did not establish residency"

    h2d = obs.counter("replay.h2d_bytes")
    appends = obs.counter("replay.resident_appends")
    fallbacks = obs.counter("replay.resident_fallbacks")
    h2d0, app0, fb0 = h2d.value, appends.value, fallbacks.value

    d = 20
    txn = t.start_transaction()
    for j in range(d):
        txn.add_file(AddFile(
            path=f"inc_{j}.parquet", partitionValues={}, size=50,
            modificationTime=5000, dataChange=True))
    txn.commit()
    snap2 = t.update()
    assert snap2.version == snap.version + 1

    assert appends.value == app0 + 1
    assert fallbacks.value == fb0
    # exact link accounting for the advance: the append ships the
    # scatter indexes + local codes, (4 + 4) bytes per padded delta
    # slot per shard — a constant in the resident base size
    d_pad = max(128, 1 << (d - 1).bit_length())
    assert h2d.value - h2d0 == 8 * 8 * d_pad
    # ownership moved to the advanced snapshot
    assert snap2._state.resident is res
    assert snap._state.resident is None

    # warm and cold states order each commit's rows differently (the
    # incremental columnarizer batches adds before removes, the full
    # parse keeps JSON order), so compare per-(path, version) decisions
    # rather than raw mask positions
    def signature(st):
        fa = st.file_actions_raw
        return sorted(zip(
            fa.column("path").to_pylist(), fa.column("dv_id").to_pylist(),
            fa.column("version").to_pylist(), fa.column("order").to_pylist(),
            np.asarray(st.live_mask).tolist(),
            np.asarray(st.tombstone_mask).tolist()))

    cold = Table.for_path(
        str(tmp_path), TpuEngine(replay_shards=8)).latest_snapshot()
    st, cst = snap2._state, cold.state
    assert signature(st) == signature(cst)
    assert (st.num_files, st.size_in_bytes) == \
        (cst.num_files, cst.size_in_bytes)


def test_resident_append_fallbacks(tmp_path):
    """Batches the resident state cannot express — stale base, DV rows,
    versions older than the resident tail — return None (host fallback)
    and count; in-batch disorder is sorted away, not rejected."""
    import pyarrow as pa

    from delta_tpu import obs

    t = _tpu_table(tmp_path, 6)
    snap = t.latest_snapshot()
    _ = snap.state.live_mask
    res = snap._state.resident
    assert res is not None
    fb = obs.counter("replay.resident_fallbacks")
    f0 = fb.value

    def delta(paths, dvs, vers, orders):
        return pa.table({
            "path": pa.array(paths, pa.string()),
            "dv_id": pa.array(dvs, pa.string()),
            "version": pa.array(vers, pa.int64()),
            "order": pa.array(orders, pa.int32()),
            "is_add": pa.array([True] * len(paths)),
        })

    good = delta(["z.parquet"], [None], [99], [0])
    assert res.append(good, n_prev=res.n + 5) is None          # stale base
    dv = delta(["z.parquet"], ["dv-1"], [99], [0])
    assert res.append(dv, n_prev=res.n) is None                # DV row
    # in-batch disorder is expressible — a real commit's removes
    # columnarize after its adds with smaller order values
    ooo = delta(["a", "b"], [None, None], [99, 98], [1, 0])
    masks = res.append(ooo, n_prev=res.n)
    assert masks is not None and len(masks[0]) == res.n
    # ...but a whole batch older than the resident tail is not: its
    # slots would sort after rows that should outrank it
    stale = delta(["c"], [None], [5], [0])
    assert res.append(stale, n_prev=res.n) is None
    assert fb.value == f0 + 3
    assert res.key_sh is not None  # fallbacks don't corrupt the state


# ---------------------------------------------------- digest parity matrix


@pytest.mark.parametrize("kind", ["fa", "dv_heavy", "hashed", "permuted"])
def test_digest_parity_matrix(kind):
    """The full route matrix — sharded at S=1/2/8, the single-chip
    kernel, and the host reference — produces bit-identical live and
    tombstone masks on the same log, for FA, DV-heavy, raw-hashed, and
    non-chronological streams."""
    from delta_tpu.ops.replay import replay_select

    rng = np.random.default_rng(1234)
    n = 24_000
    pk, dk, ver, order, add, size = _matrix_stream(kind, rng, n)

    live_h, tomb_h = python_replay_reference(
        list(zip(pk.tolist(), dk.tolist())), ver, order, add)
    want = _mask_digest(live_h, tomb_h)

    live_1, tomb_1 = replay_select([pk, dk], ver, order, add)
    assert _mask_digest(live_1, tomb_1) == want, f"single-chip: {kind}"

    for s in (1, 2, 8):
        mesh = make_mesh(n_devices=s)
        live, tomb, num_live, _ = sharded_replay_select(
            pk, dk, ver, order, add, size, mesh)
        assert _mask_digest(live, tomb) == want, f"S={s}: {kind}"
        assert num_live == int(live_h.sum())


# ------------------------------------------- resident lock discipline


def test_resident_append_and_release_serialize():
    """Regression for the serve-cache evict-during-append race: both
    append() and release() must run their bodies under the state's own
    lock, so an eviction landing mid-append can't tear the device lane
    down beneath the refresh that is still using it."""
    import threading

    from delta_tpu.parallel.resident import ResidentShardState

    st = object.__new__(ResidentShardState)
    st._lock = threading.Lock()
    st.key_sh = None
    st._hbm_bytes = 0
    seen = []

    def spying_locked(self, delta_fa, n_prev):
        seen.append(("append", self._lock.locked()))
        return None

    orig = ResidentShardState._append_locked
    ResidentShardState._append_locked = spying_locked
    try:
        assert st.append(None, 0) is None
    finally:
        ResidentShardState._append_locked = orig
    assert seen == [("append", True)]
    assert not st._lock.locked()  # released on the way out

    # release() with no lane is a no-op but must still be serialized:
    # it cannot run while an append holds the lock
    st._lock.acquire()
    blocked = threading.Event()
    done = threading.Event()

    def try_release():
        blocked.set()
        st.release()
        done.set()

    t = threading.Thread(target=try_release)
    t.start()
    blocked.wait(5)
    assert not done.wait(0.1)  # release waits on the held lock
    st._lock.release()
    assert done.wait(5)
    t.join()

"""Protocol-conformance fixtures: hand-written `_delta_log`s with known
expected states (the rebuild's golden-table mechanism — reference
`GoldenTables.scala` pattern, but the logs are constructed directly from
PROTOCOL.md semantics so both engines are checked against the spec, not
against themselves)."""

import json
import os

import numpy as np
import pyarrow as pa
import pytest

from delta_tpu.engine.host import HostEngine
from delta_tpu.engine.tpu import TpuEngine
from delta_tpu.table import Table

PROTOCOL = {"protocol": {"minReaderVersion": 1, "minWriterVersion": 2}}
METADATA = {
    "metaData": {
        "id": "test-table",
        "format": {"provider": "parquet", "options": {}},
        "schemaString": json.dumps(
            {
                "type": "struct",
                "fields": [
                    {"name": "x", "type": "long", "nullable": True, "metadata": {}}
                ],
            }
        ),
        "partitionColumns": [],
        "configuration": {},
    }
}


def write_log(path, commits):
    """commits: list of list-of-action-dicts; index == version."""
    log = os.path.join(path, "_delta_log")
    os.makedirs(log, exist_ok=True)
    for v, actions in enumerate(commits):
        with open(os.path.join(log, f"{v:020d}.json"), "w") as f:
            for a in actions:
                f.write(json.dumps(a) + "\n")
    return path


def add(path, size=100, dv=None, **kw):
    d = {
        "path": path,
        "partitionValues": {},
        "size": size,
        "modificationTime": 1,
        "dataChange": True,
        **kw,
    }
    if dv:
        d["deletionVector"] = dv
    return {"add": d}


def remove(path, dv=None, **kw):
    d = {"path": path, "deletionTimestamp": 100, "dataChange": True, **kw}
    if dv:
        d["deletionVector"] = dv
    return {"remove": d}


ENGINES = [HostEngine, TpuEngine]


def snapshot(path, engine_cls):
    return Table.for_path(path, engine_cls()).latest_snapshot()


def live_paths(snap):
    return sorted(snap.state.add_files_table.column("path").to_pylist())


@pytest.mark.parametrize("engine_cls", ENGINES)
def test_basic_reconciliation(tmp_path, engine_cls):
    path = write_log(
        str(tmp_path),
        [
            [PROTOCOL, METADATA, add("a"), add("b")],
            [add("c"), remove("a")],
            [remove("b"), add("b2")],
        ],
    )
    snap = snapshot(path, engine_cls)
    assert live_paths(snap) == ["b2", "c"]
    tombs = sorted(snap.state.tombstones_table.column("path").to_pylist())
    assert tombs == ["a", "b"]
    assert snap.num_files == 2
    assert snap.version == 2


@pytest.mark.parametrize("engine_cls", ENGINES)
def test_readd_and_same_commit_order(tmp_path, engine_cls):
    path = write_log(
        str(tmp_path),
        [
            [PROTOCOL, METADATA, add("a")],
            [remove("a"), add("a")],   # remove then re-add in one commit
            [add("b"), remove("b")],   # add then remove in one commit
        ],
    )
    snap = snapshot(path, engine_cls)
    assert live_paths(snap) == ["a"]
    assert sorted(snap.state.tombstones_table.column("path").to_pylist()) == ["b"]


@pytest.mark.parametrize("engine_cls", ENGINES)
def test_dv_identity(tmp_path, engine_cls):
    dv1 = {"storageType": "u", "pathOrInlineDv": "ab" + "x" * 20, "sizeInBytes": 4,
           "cardinality": 2, "offset": 1}
    path = write_log(
        str(tmp_path),
        [
            [PROTOCOL, METADATA, add("a")],
            # replacing (a, no-dv) with (a, dv1): remove old key, add new
            [remove("a"), add("a", dv=dv1)],
        ],
    )
    snap = snapshot(path, engine_cls)
    files = snap.state.add_files()
    assert len(files) == 1
    assert files[0].deletionVector is not None
    assert files[0].dv_unique_id.startswith("uab")
    assert "@1" in files[0].dv_unique_id


@pytest.mark.parametrize("engine_cls", ENGINES)
def test_latest_metadata_protocol_txn_domain_win(tmp_path, engine_cls):
    meta2 = json.loads(json.dumps(METADATA))
    meta2["metaData"]["configuration"] = {"foo": "bar"}
    path = write_log(
        str(tmp_path),
        [
            [PROTOCOL, METADATA, add("a"),
             {"txn": {"appId": "app", "version": 1}},
             {"domainMetadata": {"domain": "d1", "configuration": "v1",
                                 "removed": False}}],
            [meta2,
             {"txn": {"appId": "app", "version": 7}},
             {"domainMetadata": {"domain": "d1", "configuration": "",
                                 "removed": True}},
             {"domainMetadata": {"domain": "d2", "configuration": "v2",
                                 "removed": False}}],
        ],
    )
    snap = snapshot(path, engine_cls)
    assert snap.metadata.configuration == {"foo": "bar"}
    assert snap.set_transaction_version("app") == 7
    assert snap.domain_metadata("d1") is None          # tombstoned
    assert snap.domain_metadata("d2").configuration == "v2"
    # tombstone still tracked in raw state (for checkpoint retention)
    assert snap.state.domain_metadata["d1"].removed


@pytest.mark.parametrize("engine_cls", ENGINES)
def test_unknown_actions_and_fields_ignored(tmp_path, engine_cls):
    path = write_log(
        str(tmp_path),
        [
            [PROTOCOL, METADATA,
             {"futureAction": {"x": 1}},
             {"add": {"path": "a", "partitionValues": {}, "size": 1,
                      "modificationTime": 1, "dataChange": True,
                      "mysteryField": [1, 2, 3]}}],
        ],
    )
    snap = snapshot(path, engine_cls)
    assert live_paths(snap) == ["a"]


@pytest.mark.parametrize("engine_cls", ENGINES)
def test_percent_encoded_paths(tmp_path, engine_cls):
    path = write_log(
        str(tmp_path),
        [
            [PROTOCOL, METADATA, add("p%3D1/a%20b.parquet")],
            [remove("p%3D1/a%20b.parquet")],
            [add("x%25y.parquet")],
        ],
    )
    snap = snapshot(path, engine_cls)
    # decoded path; the encoded add and remove refer to the same file
    assert live_paths(snap) == ["x%y.parquet"]


@pytest.mark.parametrize("engine_cls", ENGINES)
def test_checkpoint_plus_tail(tmp_path, engine_cls):
    """Replay = checkpoint state + later commits override it."""
    path = write_log(
        str(tmp_path),
        [
            [PROTOCOL, METADATA, add("a"), add("b")],
            [add("c")],
            [remove("c"), add("d")],
        ],
    )
    table = Table.for_path(path, engine_cls())
    table.checkpoint(1)  # checkpoint at v1: {a, b, c}
    snap = Table.for_path(path, engine_cls()).latest_snapshot()
    assert snap.log_segment.checkpoint_version == 1
    assert len(snap.log_segment.deltas) == 1
    assert live_paths(snap) == ["a", "b", "d"]


@pytest.mark.parametrize("engine_cls", ENGINES)
def test_multipart_checkpoint(tmp_path, engine_cls):
    from delta_tpu.config import settings

    path = write_log(
        str(tmp_path),
        [
            [PROTOCOL, METADATA] + [add(f"f{i}") for i in range(10)],
            [remove("f0")],
        ],
    )
    table = Table.for_path(path, engine_cls())
    old = settings.checkpoint_part_size
    settings.checkpoint_part_size = 4
    try:
        table.checkpoint(1)
    finally:
        settings.checkpoint_part_size = old
    log = os.path.join(path, "_delta_log")
    parts = [f for f in os.listdir(log) if ".checkpoint.00" in f]
    # part 1 holds only the small actions (protocol/metaData), then
    # 10 file actions in fixed chunks of 4 -> 3 file parts
    assert len(parts) == 4
    snap = Table.for_path(path, engine_cls()).latest_snapshot()
    assert snap.log_segment.checkpoint_version == 1
    assert len(snap.log_segment.checkpoints) == 4
    assert live_paths(snap) == [f"f{i}" for i in range(1, 10)]


@pytest.mark.parametrize("engine_cls", ENGINES)
def test_v2_checkpoint_with_sidecar(tmp_path, engine_cls):
    path = write_log(
        str(tmp_path),
        [
            [PROTOCOL, METADATA, add("a"), add("b")],
            [remove("a"), add("c")],
        ],
    )
    table = Table.for_path(path, engine_cls())
    from delta_tpu.log.checkpointer import write_checkpoint

    write_checkpoint(table.engine, table.latest_snapshot(), policy="v2")
    log = os.path.join(path, "_delta_log")
    assert os.path.isdir(os.path.join(log, "_sidecars"))
    top = [f for f in os.listdir(log) if ".checkpoint." in f and f.endswith(".parquet")]
    assert len(top) == 1  # the UUID top-level file; file actions in _sidecars/
    snap = Table.for_path(path, engine_cls()).latest_snapshot()
    assert snap.log_segment.checkpoint_version == 1
    assert live_paths(snap) == ["b", "c"]


@pytest.mark.parametrize("engine_cls", ENGINES)
def test_compacted_delta_substitution(tmp_path, engine_cls):
    path = write_log(
        str(tmp_path),
        [
            [PROTOCOL, METADATA, add("a")],
            [add("b")],
            [remove("a"), add("c")],
            [add("d")],
        ],
    )
    from delta_tpu.log.cleanup import write_compacted_delta

    table = Table.for_path(path, engine_cls())
    write_compacted_delta(table, 1, 2)
    snap = Table.for_path(path, engine_cls()).latest_snapshot()
    assert len(snap.log_segment.compacted_deltas) == 1
    # singles 1,2 replaced by the compacted file
    assert [os.path.basename(f.path) for f in snap.log_segment.deltas] == [
        "00000000000000000000.json",
        "00000000000000000003.json",
    ]
    assert live_paths(snap) == ["b", "c", "d"]
    tombs = snap.state.tombstones_table.column("path").to_pylist()
    assert tombs == ["a"]


@pytest.mark.parametrize("engine_cls", ENGINES)
def test_stats_surfaced(tmp_path, engine_cls):
    stats = json.dumps(
        {"numRecords": 3, "minValues": {"x": 1}, "maxValues": {"x": 9},
         "nullCount": {"x": 0}}
    )
    path = write_log(
        str(tmp_path),
        [[PROTOCOL, METADATA, add("a", stats=stats)]],
    )
    snap = snapshot(path, engine_cls)
    files = snap.state.add_files()
    assert files[0].num_records() == 3
    from delta_tpu.expressions import col, lit

    assert snap.scan(filter=col("x") > lit(10)).add_files_table().num_rows == 0
    assert snap.scan(filter=col("x") > lit(5)).add_files_table().num_rows == 1


def test_engines_agree_on_random_history(tmp_path):
    """Fuzz: random add/remove interleavings must reconstruct identically
    on both engines."""
    rng = np.random.default_rng(0)
    commits = [[PROTOCOL, METADATA]]
    alive = set()
    for v in range(30):
        actions = []
        for _ in range(rng.integers(1, 8)):
            if alive and rng.random() < 0.4:
                p = sorted(alive)[rng.integers(0, len(alive))]
                actions.append(remove(p))
                alive.discard(p)
            else:
                p = f"f{rng.integers(0, 40)}"
                actions.append(add(p))
                alive.add(p)
        commits.append(actions)
    path = write_log(str(tmp_path), commits)
    host = snapshot(path, HostEngine)
    tpu = snapshot(path, TpuEngine)
    assert live_paths(host) == live_paths(tpu)
    assert host.num_files == tpu.num_files
    assert host.size_in_bytes == tpu.size_in_bytes


@pytest.mark.parametrize("engine_cls", ENGINES)
def test_v2_checkpoint_multiple_sidecars(tmp_path, engine_cls):
    """With checkpoint_part_size set, a V2 checkpoint splits file actions
    across several concurrently-written sidecars, all resolved on read."""
    from delta_tpu.config import settings
    from delta_tpu.log.checkpointer import write_checkpoint

    path = write_log(
        str(tmp_path),
        [
            [PROTOCOL, METADATA] + [add(f"f{i}") for i in range(9)],
            [remove("f0")],
        ],
    )
    table = Table.for_path(path, engine_cls())
    old = settings.checkpoint_part_size
    settings.checkpoint_part_size = 4
    try:
        write_checkpoint(table.engine, table.latest_snapshot(), policy="v2")
    finally:
        settings.checkpoint_part_size = old
    log = os.path.join(path, "_delta_log")
    sidecars = os.listdir(os.path.join(log, "_sidecars"))
    # 8 live adds (f0 removed; its tombstone ages out of retention), 4/part
    assert len(sidecars) == 2
    snap = Table.for_path(path, engine_cls()).latest_snapshot()
    assert snap.log_segment.checkpoint_version == 1
    assert live_paths(snap) == [f"f{i}" for i in range(1, 9)]


def test_checkpoint_stats_shaping(tmp_path):
    """delta.checkpoint.writeStatsAsJson/writeStatsAsStruct control the
    checkpoint add-row stats forms (`Checkpoints.scala` buildCheckpoint)."""
    import pyarrow.parquet as pq
    import delta_tpu.api as dta
    import numpy as np
    import pyarrow as pa

    def make(path, props):
        dta.write_table(path, pa.table(
            {"x": pa.array(np.arange(5, dtype=np.int64))}), properties=props)
        t = Table.for_path(path)
        t.checkpoint()
        log = os.path.join(path, "_delta_log")
        cp = [f for f in os.listdir(log) if f.endswith(".checkpoint.parquet")]
        return pq.read_table(os.path.join(log, cp[0]))

    # struct form on: stats_parsed present with parsed minValues
    tbl = make(str(tmp_path / "t1"),
               {"delta.checkpoint.writeStatsAsStruct": "true"})
    add_t = tbl.column("add").combine_chunks()
    assert "stats_parsed" in [f.name for f in add_t.type]
    import pyarrow.compute as pc
    sp = pc.struct_field(add_t, "stats_parsed")
    rows = [r for r in sp.to_pylist() if r and r.get("numRecords")]
    assert rows and rows[0]["numRecords"] == 5
    assert rows[0]["minValues"]["x"] == 0

    # json off: stats column all-null in the checkpoint
    tbl2 = make(str(tmp_path / "t2"),
                {"delta.checkpoint.writeStatsAsJson": "false"})
    add2 = tbl2.column("add").combine_chunks()
    stats2 = pc.struct_field(add2, "stats")
    assert all(s is None for s in stats2.to_pylist())


def test_set_transaction_checkpoint_retention(tmp_path):
    """delta.setTransactionRetentionDuration expires idle SetTransaction
    entries from checkpoints (`InMemoryLogReplay.scala:84-91`)."""
    import time as _time
    import delta_tpu.api as dta
    import numpy as np
    import pyarrow as pa
    from delta_tpu.streaming import DeltaSink

    path = str(tmp_path / "t")
    dta.write_table(
        path, pa.table({"x": pa.array(np.arange(3, dtype=np.int64))}),
        properties={"delta.setTransactionRetentionDuration": "interval 1 millisecond"})
    DeltaSink(path, query_id="old-stream").add_batch(0, pa.table(
        {"x": pa.array([10], pa.int64())}))
    _time.sleep(0.05)  # let the entry age past the 1ms retention
    t = Table.for_path(path)
    t.checkpoint()
    snap = Table.for_path(path).latest_snapshot()
    assert "old-stream" not in snap.state.set_transactions


def test_randomized_file_prefixes(tmp_path):
    import delta_tpu.api as dta
    import numpy as np
    import pyarrow as pa

    path = str(tmp_path / "t")
    dta.write_table(
        path, pa.table({"x": pa.array(np.arange(10, dtype=np.int64))}),
        properties={"delta.randomizeFilePrefixes": "true",
                    "delta.randomPrefixLength": "3"})
    snap = Table.for_path(path).latest_snapshot()
    paths = snap.state.add_files_table.column("path").to_pylist()
    for p in paths:
        bucket, _, rest = p.partition("/")
        assert len(bucket) == 3 and rest.startswith("part-"), p
    assert dta.read_table(path).num_rows == 10


def test_stats_struct_only_checkpoint_keeps_skipping(tmp_path):
    """The reference-recommended combo writeStatsAsJson=false +
    writeStatsAsStruct=true: after checkpointing, stats survive via the
    struct form and data skipping still prunes."""
    import delta_tpu.api as dta
    import numpy as np
    import pyarrow as pa
    from delta_tpu.expressions import col, lit

    path = str(tmp_path / "t")
    props = {"delta.checkpoint.writeStatsAsJson": "false",
             "delta.checkpoint.writeStatsAsStruct": "true"}
    dta.write_table(path, pa.table(
        {"x": pa.array(np.arange(10, dtype=np.int64))}), properties=props)
    dta.write_table(path, pa.table(
        {"x": pa.array(np.arange(100, 110, dtype=np.int64))}), mode="append")
    Table.for_path(path).checkpoint()
    snap = Table.for_path(path).latest_snapshot()
    stats = [s for s in
             snap.state.add_files_table.column("stats").to_pylist() if s]
    assert len(stats) == 2  # reconstructed from stats_parsed
    assert json.loads(sorted(stats)[0])["minValues"]["x"] == 0
    files = snap.scan(filter=col("x") >= lit(100)).files()
    assert len(files) == 1


def test_ict_monotonic_through_fast_path(tmp_path):
    """In-commit timestamps stay strictly increasing across commits even
    when the previous snapshot's timestamp came from the .crc/P&M fast
    path (the monotonicity floor feeds the next commit's ICT)."""
    import delta_tpu.api as dta
    import numpy as np
    import pyarrow as pa
    from delta_tpu.read.cdc import COMMIT_VERSION_COL  # noqa: F401

    path = str(tmp_path / "ict")
    dta.write_table(path, pa.table(
        {"x": pa.array(np.arange(3, dtype=np.int64))}),
        properties={"delta.enableInCommitTimestamps": "true"})
    for i in range(4):
        # fresh handle each time: the read snapshot resolves via crc
        dta.write_table(path, pa.table(
            {"x": pa.array([i], pa.int64())}), mode="append")
    snap = Table.for_path(path).latest_snapshot()
    icts = [ci.inCommitTimestamp
            for v, ci in sorted(snap.state.commit_infos.items())
            if ci.inCommitTimestamp is not None]
    assert len(icts) >= 2
    assert all(b > a for a, b in zip(icts, icts[1:])), icts


def test_column_mapping_id_mode_roundtrip(tmp_path):
    import delta_tpu.api as dta
    import numpy as np
    import pyarrow as pa

    path = str(tmp_path / "idmode")
    dta.write_table(path, pa.table(
        {"a": pa.array(np.arange(5, dtype=np.int64)),
         "b": pa.array(["x"] * 5)}),
        properties={"delta.columnMapping.mode": "id"})
    out = dta.read_table(path)
    assert sorted(out.column_names) == ["a", "b"]
    assert out.num_rows == 5
    snap = Table.for_path(path).latest_snapshot()
    ids = {f.name: f.metadata.get("delta.columnMapping.id")
           for f in snap.schema.fields}
    assert all(v is not None for v in ids.values())
    # physical names differ from logical under id mode too
    phys = {f.name: f.metadata.get("delta.columnMapping.physicalName")
            for f in snap.schema.fields}
    assert all(v for v in phys.values())


def test_stats_struct_checkpoint_preserves_tight_bounds(tmp_path):
    """tightBounds written by a DV-capable foreign engine must survive a
    writeStatsAsJson=false checkpoint round-trip through stats_parsed."""
    import delta_tpu.api as dta
    import numpy as np
    import pyarrow as pa

    path = str(tmp_path / "t")
    props = {"delta.checkpoint.writeStatsAsJson": "false",
             "delta.checkpoint.writeStatsAsStruct": "true"}
    dta.write_table(path, pa.table(
        {"x": pa.array(np.arange(10, dtype=np.int64))}), properties=props)
    commit = os.path.join(path, "_delta_log", "%020d.json" % 0)
    out_lines = []
    with open(commit) as f:
        for ln in f.read().splitlines():
            d = json.loads(ln)
            if "add" in d and d["add"].get("stats"):
                st = json.loads(d["add"]["stats"])
                st["tightBounds"] = True
                d["add"]["stats"] = json.dumps(st, separators=(",", ":"))
            out_lines.append(json.dumps(d, separators=(",", ":")))
    with open(commit, "w") as f:
        f.write("\n".join(out_lines) + "\n")
    Table.for_path(path).checkpoint()
    snap = Table.for_path(path).latest_snapshot()
    stats = [json.loads(s) for s in
             snap.state.add_files_table.column("stats").to_pylist() if s]
    assert stats and all(s.get("tightBounds") is True for s in stats)

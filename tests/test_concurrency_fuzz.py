"""Conflict-matrix pair races + randomized multi-writer fuzz.

Reference analogue: `OptimizeConflictSuite` / `ConflictChecker.scala`'s
taxonomy driven through the phase-locking fuzzer
(`fuzzer/OptimisticTransactionPhases.scala`). The pair tests park one
writer at a precise phase (including the new `after_prepare` boundary),
let the other win, and assert the loser's exact outcome per the conflict
matrix. The randomized fuzz runs 4 writers with a seeded release
schedule and checks global invariants: contiguous unique versions, only
taxonomy errors, no double-delete of any file in the committed log, and
engine/oracle agreement on the final state.
"""

import json
import os
import random
import threading

import numpy as np
import pyarrow as pa
import pytest

import delta_tpu.api as dta
from delta_tpu.concurrency import PhaseLockingObserver, run_txn_async
from delta_tpu.errors import (
    ConcurrentAppendError,
    ConcurrentDeleteDeleteError,
    ConcurrentDeleteReadError,
    ConcurrentModificationError,
    ConcurrentTransactionError,
    MetadataChangedError,
    ProtocolChangedError,
)
from delta_tpu.models.actions import AddFile
from delta_tpu.table import Table

TAXONOMY = (
    ConcurrentAppendError, ConcurrentDeleteDeleteError,
    ConcurrentDeleteReadError, ConcurrentTransactionError,
    MetadataChangedError, ProtocolChangedError,
)


def _batch(start, n):
    return pa.table({"id": pa.array(np.arange(start, start + n,
                                              dtype=np.int64))})


def _add(path, size=10, data_change=True):
    return AddFile(path=path, size=size, modificationTime=1,
                   dataChange=data_change)


def _optimize_txn(table, victims, out_name):
    """Emulate OPTIMIZE's transaction shape: read the table, remove the
    compacted inputs (dataChange=False), add the coalesced output."""
    txn = table.start_transaction("OPTIMIZE")
    txn.scan_files()
    for f in victims:
        txn.remove_file(f.remove(deletion_timestamp=1, data_change=False))
    txn.add_file(_add(out_name, size=sum(f.size for f in victims),
                      data_change=False))
    return txn


def _delete_txn(table, victim):
    txn = table.start_transaction("DELETE")
    txn.remove_file(victim.remove(deletion_timestamp=2))
    return txn


# ------------------------------------------------------------ matrix pairs


def test_optimize_loses_to_delete_of_same_file(tmp_table_path):
    """delete x optimize: the winner deleted a file the optimizer READ
    (its compaction input) -> ConcurrentDeleteReadError — the read-set
    check fires before the remove-set check, exactly the reference's
    `ConflictChecker.scala:584` ordering for OptimizeConflictSuite."""
    dta.write_table(tmp_table_path, _batch(0, 10), target_rows_per_file=5)
    table = Table.for_path(tmp_table_path)
    files = table.latest_snapshot().state.add_files()
    assert len(files) >= 2

    obs = PhaseLockingObserver(block_after_prepare=True)
    opt = _optimize_txn(table, files[:2], "compacted-a.parquet")
    opt.observer = obs
    thread = run_txn_async(opt.commit)
    obs.after_prepare_barrier.wait_for_arrival()  # fully prepared, unwritten

    _delete_txn(table, files[0]).commit()

    obs.after_prepare_barrier.unblock()
    with pytest.raises(ConcurrentDeleteReadError):
        thread.join_result()


def test_delete_loses_to_optimize_of_same_file(tmp_table_path):
    dta.write_table(tmp_table_path, _batch(0, 10), target_rows_per_file=5)
    table = Table.for_path(tmp_table_path)
    files = table.latest_snapshot().state.add_files()

    obs = PhaseLockingObserver(block_before_commit=True)
    dele = _delete_txn(table, files[0])
    dele.observer = obs
    thread = run_txn_async(dele.commit)
    obs.before_commit_barrier.wait_for_arrival()

    _optimize_txn(table, files, "compacted-b.parquet").commit()

    obs.before_commit_barrier.unblock()
    with pytest.raises(ConcurrentDeleteDeleteError):
        thread.join_result()


def test_optimize_survives_concurrent_append(tmp_table_path):
    """append x optimize: disjoint files -> the optimizer rebases and
    commits (appends don't invalidate a compaction's inputs under
    WriteSerializable)."""
    dta.write_table(tmp_table_path, _batch(0, 10), target_rows_per_file=5)
    table = Table.for_path(tmp_table_path)
    files = table.latest_snapshot().state.add_files()

    obs = PhaseLockingObserver(block_after_prepare=True)
    opt = _optimize_txn(table, files, "compacted-c.parquet")
    opt.observer = obs
    thread = run_txn_async(opt.commit)
    obs.after_prepare_barrier.wait_for_arrival()

    txn_b = table.start_transaction()
    txn_b.add_file(_add("fresh.parquet"))
    res_b = txn_b.commit()

    obs.after_prepare_barrier.unblock()
    res = thread.join_result()
    assert res.version == res_b.version + 1
    paths = set(table.latest_snapshot().state.add_files_table
                .column("path").to_pylist())
    assert "compacted-c.parquet" in paths and "fresh.parquet" in paths
    assert not any(f.path in paths for f in files)


def test_metadata_change_beats_optimize(tmp_table_path):
    import dataclasses

    dta.write_table(tmp_table_path, _batch(0, 10), target_rows_per_file=5)
    table = Table.for_path(tmp_table_path)
    files = table.latest_snapshot().state.add_files()

    obs = PhaseLockingObserver(block_before_commit=True)
    opt = _optimize_txn(table, files, "compacted-d.parquet")
    opt.observer = obs
    thread = run_txn_async(opt.commit)
    obs.before_commit_barrier.wait_for_arrival()

    txn_m = table.start_transaction("SET TBLPROPERTIES")
    meta = txn_m.metadata()
    txn_m.update_metadata(dataclasses.replace(
        meta, configuration={**meta.configuration, "foo": "bar"}))
    txn_m.commit()

    obs.before_commit_barrier.unblock()
    with pytest.raises(MetadataChangedError):
        thread.join_result()


def test_backfill_phase_hook_fires_for_coordinated_commits(coordinated_path):
    table = Table.for_path(coordinated_path)
    obs = PhaseLockingObserver()  # all barriers pass-through; events record
    txn = table.start_transaction()
    txn.add_file(_add("cc.parquet"))
    txn.observer = obs
    txn.commit()
    kinds = [k for k, _ in obs.events]
    assert kinds == ["attempt", "prepared", "backfilled", "committed"]


# --------------------------------------------------------- randomized fuzz


@pytest.mark.parametrize("seed", [7, 21, 1234])
def test_multi_writer_fuzz(tmp_table_path, seed):
    """4 writers, randomized release order, mixed op types. Invariants:
    contiguous unique versions; every failure is a taxonomy error; no
    file removed twice in the committed log without an interleaving
    re-add; both engines agree with the independent oracle at the end."""
    rng = random.Random(seed)
    dta.write_table(tmp_table_path, _batch(0, 40), target_rows_per_file=5)
    table = Table.for_path(tmp_table_path)
    base_files = table.latest_snapshot().state.add_files()
    assert len(base_files) == 8

    def writer(kind, i):
        t = Table.for_path(tmp_table_path)  # fresh snapshot per writer
        if kind == "append":
            txn = t.start_transaction()
            txn.add_file(_add(f"app-{i}.parquet"))
        elif kind == "delete":
            txn = _delete_txn(t, base_files[i % len(base_files)])
        elif kind == "optimize":
            fs = t.latest_snapshot().state.add_files()
            victims = [f for f in fs if f.path.startswith("part-")][:2]
            if not victims:
                txn = t.start_transaction()
                txn.add_file(_add(f"app-x{i}.parquet"))
            else:
                txn = _optimize_txn(t, victims, f"opt-{i}.parquet")
        elif kind == "metadata":
            import dataclasses

            txn = t.start_transaction("SET TBLPROPERTIES")
            meta = txn.metadata()
            txn.update_metadata(dataclasses.replace(
                meta,
                configuration={**meta.configuration, f"k{i}": str(i)}))
        else:  # txn
            txn = t.start_transaction("STREAMING UPDATE")
            txn.set_transaction(f"app{i % 2}", i)
            txn.add_file(_add(f"stream-{i}.parquet"))
        obs = PhaseLockingObserver(block_before_commit=True)
        txn.observer = obs
        return txn, obs

    kinds = ["append", "delete", "optimize", "metadata", "txn"]
    picks = [rng.choice(kinds) for _ in range(4)]
    txns = [writer(k, i) for i, k in enumerate(picks)]
    threads = [run_txn_async(txn.commit) for txn, _ in txns]
    for _, obs in txns:
        obs.before_commit_barrier.wait_for_arrival()
    order = list(range(4))
    rng.shuffle(order)
    for j in order:
        txns[j][1].before_commit_barrier.unblock()

    outcomes = []
    for th in threads:
        try:
            outcomes.append(("ok", th.join_result(timeout=120)))
        except ConcurrentModificationError as e:
            assert isinstance(e, TAXONOMY), type(e)
            outcomes.append(("conflict", e))

    committed = sorted(r.version for s, r in outcomes if s == "ok")
    assert len(set(committed)) == len(committed), "duplicate commit version"
    if committed:
        assert committed == list(range(committed[0], committed[-1] + 1)), \
            "committed versions not contiguous"

    # raw-log invariant: a path is never removed twice without a re-add
    log = os.path.join(tmp_table_path, "_delta_log")
    state = {}
    for name in sorted(os.listdir(log)):
        if not name.endswith(".json") or "." in name[:-5]:
            continue
        with open(os.path.join(log, name)) as f:
            for ln in f:
                if not ln.strip():
                    continue
                act = json.loads(ln)
                if "add" in act:
                    state[act["add"]["path"]] = "live"
                elif "remove" in act:
                    p = act["remove"]["path"]
                    assert state.get(p) != "removed", \
                        f"{p} removed twice in the committed log"
                    state[p] = "removed"

    # final state: engines agree with the independent oracle
    from delta_tpu.engine.host import HostEngine
    from delta_tpu.engine.tpu import TpuEngine

    from tests.independent_oracle import read_table_state

    oracle = read_table_state(tmp_table_path).summary()
    for eng in (HostEngine(), TpuEngine()):
        snap = Table.for_path(tmp_table_path, eng).latest_snapshot()
        mine = sorted(snap.state.add_files_table.column("path").to_pylist())
        theirs = sorted(k.split("|")[0] for k in oracle["live_keys"])
        assert mine == theirs

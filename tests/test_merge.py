import numpy as np
import pyarrow as pa
import pytest

import delta_tpu.api as dta
from delta_tpu.commands.merge import MergeCardinalityError, merge
from delta_tpu.errors import DeltaError
from delta_tpu.expressions import col, lit
from delta_tpu.table import Table


@pytest.fixture
def target_path(tmp_table_path):
    data = pa.table(
        {
            "id": pa.array([1, 2, 3, 4, 5], pa.int64()),
            "value": pa.array([10.0, 20.0, 30.0, 40.0, 50.0]),
            "status": pa.array(["a", "a", "a", "a", "a"]),
        }
    )
    dta.write_table(tmp_table_path, data)
    return tmp_table_path


def _source(ids, values, ops=None):
    cols = {
        "id": pa.array(ids, pa.int64()),
        "value": pa.array(values, pa.float64()),
    }
    if ops is not None:
        cols["op"] = pa.array(ops, pa.string())
    return pa.table(cols)


def test_merge_upsert(target_path):
    table = Table.for_path(target_path)
    src = _source([3, 4, 6, 7], [300.0, 400.0, 600.0, 700.0])
    m = (
        merge(table, src, on=col("target.id") == col("source.id"))
        .when_matched_update(set={"value": col("source.value")})
        .when_not_matched_insert(
            values={"id": col("source.id"), "value": col("source.value"),
                    "status": lit("new")}
        )
        .execute()
    )
    assert m.num_target_rows_updated == 2
    assert m.num_target_rows_inserted == 2
    assert m.num_target_rows_copied == 3
    out = dta.read_table(target_path).sort_by("id")
    assert out.column("id").to_pylist() == [1, 2, 3, 4, 5, 6, 7]
    assert out.column("value").to_pylist() == [10.0, 20.0, 300.0, 400.0, 50.0, 600.0, 700.0]
    st = out.column("status").to_pylist()
    assert st[5] == "new" and st[6] == "new"


def test_merge_matched_delete_with_condition(target_path):
    table = Table.for_path(target_path)
    src = _source([1, 2, 3], [0.0, 0.0, 0.0], ops=["del", "keep", "del"])
    m = (
        merge(table, src, on=col("target.id") == col("source.id"))
        .when_matched_delete(condition=col("source.op") == lit("del"))
        .when_matched_update(set={"value": col("source.value")})
        .execute()
    )
    assert m.num_target_rows_deleted == 2
    assert m.num_target_rows_updated == 1
    out = dta.read_table(target_path).sort_by("id")
    assert out.column("id").to_pylist() == [2, 4, 5]
    assert out.column("value").to_pylist()[0] == 0.0


def test_merge_clause_order_first_wins(target_path):
    table = Table.for_path(target_path)
    src = _source([1], [99.0])
    (
        merge(table, src, on=col("target.id") == col("source.id"))
        .when_matched_update(set={"value": lit(111.0)},
                             condition=col("target.value") < lit(15.0))
        .when_matched_update(set={"value": lit(222.0)})
        .execute()
    )
    out = dta.read_table(target_path).sort_by("id")
    assert out.column("value").to_pylist()[0] == 111.0


def test_merge_not_matched_by_source_delete(target_path):
    table = Table.for_path(target_path)
    src = _source([1, 2], [0.0, 0.0])
    m = (
        merge(table, src, on=col("target.id") == col("source.id"))
        .when_matched_update(set={"value": col("source.value")})
        .when_not_matched_by_source_delete()
        .execute()
    )
    assert m.num_target_rows_deleted == 3
    out = dta.read_table(target_path).sort_by("id")
    assert out.column("id").to_pylist() == [1, 2]


def test_merge_cardinality_violation(target_path):
    table = Table.for_path(target_path)
    src = _source([3, 3], [1.0, 2.0])
    with pytest.raises(MergeCardinalityError):
        (
            merge(table, src, on=col("target.id") == col("source.id"))
            .when_matched_update(set={"value": col("source.value")})
            .execute()
        )


def test_merge_insert_all(target_path):
    table = Table.for_path(target_path)
    src = pa.table(
        {
            "id": pa.array([8], pa.int64()),
            "value": pa.array([80.0]),
            "status": pa.array(["s"]),
        }
    )
    (
        merge(table, src, on=col("target.id") == col("source.id"))
        .when_not_matched_insert_all()
        .execute()
    )
    out = dta.read_table(target_path).sort_by("id")
    assert out.column("id").to_pylist() == [1, 2, 3, 4, 5, 8]
    assert out.column("status").to_pylist()[-1] == "s"


def test_merge_residual_condition(target_path):
    table = Table.for_path(target_path)
    src = _source([1, 2], [100.0, 200.0])
    (
        merge(
            table, src,
            on=(col("target.id") == col("source.id"))
            & (col("source.value") > lit(150.0)),
        )
        .when_matched_update(set={"value": col("source.value")})
        .execute()
    )
    out = dta.read_table(target_path).sort_by("id")
    vals = out.column("value").to_pylist()
    assert vals[0] == 10.0      # id=1 pair filtered out by residual
    assert vals[1] == 200.0     # id=2 updated


def test_merge_schema_evolution(tmp_table_path):
    """Extra source columns: error without with_schema_evolution(),
    evolve the target schema with it (reference withSchemaEvolution)."""
    dta.write_table(tmp_table_path, pa.table(
        {"id": pa.array([1, 2], pa.int64())}))
    src = pa.table({"id": pa.array([2, 3], pa.int64()),
                    "extra": pa.array(["e2", "e3"])})
    t = Table.for_path(tmp_table_path)
    with pytest.raises(DeltaError, match="with_schema_evolution"):
        (merge(t, src, on=col("target.id") == col("source.id"))
         .when_not_matched_insert_all().execute())

    m = (merge(Table.for_path(tmp_table_path), src,
               on=col("target.id") == col("source.id"))
         .with_schema_evolution()
         .when_matched_update_all()
         .when_not_matched_insert_all()
         .execute())
    assert m.num_target_rows_inserted == 1
    out = dta.read_table(tmp_table_path)
    rows = {i: e for i, e in zip(out.column("id").to_pylist(),
                                 out.column("extra").to_pylist())}
    assert rows == {1: None, 2: "e2", 3: "e3"}


def test_merge_evolution_with_column_mapping(tmp_table_path):
    """Evolved columns on a mapped table get field ids/physical names."""
    dta.write_table(tmp_table_path, pa.table(
        {"id": pa.array([1], pa.int64())}),
        properties={"delta.columnMapping.mode": "name"})
    src = pa.table({"id": pa.array([2], pa.int64()),
                    "extra": pa.array(["x"])})
    (merge(Table.for_path(tmp_table_path), src,
           on=col("target.id") == col("source.id"))
     .with_schema_evolution()
     .when_not_matched_insert_all()
     .execute())
    snap = Table.for_path(tmp_table_path).latest_snapshot()
    f = snap.schema["extra"]
    assert f.metadata.get("delta.columnMapping.id") is not None
    assert f.metadata.get("delta.columnMapping.physicalName")
    out = dta.read_table(tmp_table_path)
    assert dict(zip(out.column("id").to_pylist(),
                    out.column("extra").to_pylist())) == {1: None, 2: "x"}


def test_merge_case_insensitive_source_columns(tmp_table_path):
    dta.write_table(tmp_table_path, pa.table(
        {"id": pa.array([1], pa.int64())}))
    src = pa.table({"ID": pa.array([2], pa.int64())})
    (merge(Table.for_path(tmp_table_path), src,
           on=col("target.id") == col("source.ID"))
     .when_not_matched_insert_all()
     .execute())
    out = dta.read_table(tmp_table_path)
    assert sorted(out.column("id").to_pylist()) == [1, 2]  # no NULL insert


def test_merge_evolution_commits_schema_even_without_row_changes(tmp_table_path):
    dta.write_table(tmp_table_path, pa.table(
        {"id": pa.array([1], pa.int64())}))
    src = pa.table({"id": pa.array([99], pa.int64()),
                    "extra": pa.array(["x"])})
    (merge(Table.for_path(tmp_table_path), src,
           on=col("target.id") == col("source.id"))
     .with_schema_evolution()
     .when_matched_update_all()   # nothing matches
     .execute())
    snap = Table.for_path(tmp_table_path).latest_snapshot()
    assert "extra" in {f.name for f in snap.schema.fields}
    assert snap.version == 1  # metadata-only commit landed


def test_merge_evolution_explicit_assignment_to_new_column(tmp_table_path):
    dta.write_table(tmp_table_path, pa.table(
        {"id": pa.array([1], pa.int64())}))
    src = pa.table({"id": pa.array([2], pa.int64()),
                    "extra": pa.array(["x"])})
    # without evolution: error, never a silent drop
    with pytest.raises(DeltaError, match="with_schema_evolution"):
        (merge(Table.for_path(tmp_table_path), src,
               on=col("target.id") == col("source.id"))
         .when_not_matched_insert(values={"id": col("source.id"),
                                          "extra": col("source.extra")})
         .execute())
    # assignment to a column in neither schema: clean error
    with pytest.raises(DeltaError, match="neither"):
        (merge(Table.for_path(tmp_table_path), src,
               on=col("target.id") == col("source.id"))
         .with_schema_evolution()
         .when_not_matched_insert(values={"id": col("source.id"),
                                          "ghost": lit(1)})
         .execute())
    (merge(Table.for_path(tmp_table_path), src,
           on=col("target.id") == col("source.id"))
     .with_schema_evolution()
     .when_not_matched_insert(values={"id": col("source.id"),
                                      "extra": col("source.extra")})
     .execute())
    out = dta.read_table(tmp_table_path)
    assert dict(zip(out.column("id").to_pylist(),
                    out.column("extra").to_pylist())) == {1: None, 2: "x"}


def test_merge_prunes_target_files_by_source_bounds(tmp_table_path):
    """Equi-key source bounds prune target files (dynamic pruning); with
    a not-matched-by-source clause the whole table must be scanned."""
    dta.write_table(tmp_table_path, pa.table(
        {"id": pa.array(np.arange(0, 100, dtype=np.int64)),
         "v": pa.array(np.zeros(100))}), target_rows_per_file=10)
    src = pa.table({"id": pa.array([5], pa.int64()),
                    "v": pa.array([9.0])})
    m = (merge(Table.for_path(tmp_table_path), src,
               on=col("target.id") == col("source.id"))
         .when_matched_update_all()
         .execute())
    assert m.num_target_files_scanned == 1  # 10 files, bounds hit one
    assert m.num_target_rows_updated == 1

    m2 = (merge(Table.for_path(tmp_table_path), src,
                on=col("target.id") == col("source.id"))
          .when_matched_update_all()
          .when_not_matched_by_source_update(set={"v": lit(-1.0)},
                                             condition=col("target.id") >= lit(95))
          .execute())
    assert m2.num_target_files_scanned >= 10  # no pruning allowed
    out = dta.read_table(tmp_table_path)
    vals = dict(zip(out.column("id").to_pylist(), out.column("v").to_pylist()))
    assert vals[5] == 9.0 and vals[99] == -1.0 and vals[0] == 0.0


def test_merge_null_keys_never_match(tmp_table_path):
    """SQL equi-join semantics: NULL join keys match nothing, with or
    without source-bounds pruning."""
    dta.write_table(tmp_table_path, pa.table(
        {"id": pa.array([None, None], pa.int64()),
         "v": pa.array([1.0, 2.0])}))
    dta.write_table(tmp_table_path, pa.table(
        {"id": pa.array([5], pa.int64()), "v": pa.array([3.0])}),
        mode="append")
    src = pa.table({"id": pa.array([None, 5], pa.int64()),
                    "v": pa.array([9.0, 9.0])})

    def run(extra_nmbs):
        b = (merge(Table.for_path(tmp_table_path), src,
                   on=col("target.id") == col("source.id"))
             .when_matched_update_all())
        if extra_nmbs:  # disables pruning without changing any row
            b = b.when_not_matched_by_source_update(
                set={"v": lit(99.0)}, condition=col("target.v") > lit(1e9))
        return b.execute()

    m1 = run(False)
    assert m1.num_target_rows_updated == 1  # only id=5; NULLs untouched
    m2 = run(True)
    assert m2.num_target_rows_updated == 1  # identical without pruning
    out = dta.read_table(tmp_table_path)
    vals = sorted(out.column("v").to_pylist())
    assert vals == [1.0, 2.0, 9.0]


def test_merge_nan_keys_match_null_keys_dont(tmp_table_path):
    """Spark semantics: NaN = NaN is TRUE in joins, NULL matches nothing."""
    dta.write_table(tmp_table_path, pa.table(
        {"k": pa.array([float("nan"), None, 1.0], pa.float64()),
         "v": pa.array([10.0, 20.0, 30.0])}))
    src = pa.table({"k": pa.array([float("nan"), None], pa.float64()),
                    "v": pa.array([99.0, 88.0])})
    m = (merge(Table.for_path(tmp_table_path), src,
               on=col("target.k") == col("source.k"))
         .when_matched_update_all()
         .when_not_matched_insert_all()
         .execute())
    assert m.num_target_rows_updated == 1   # the NaN row
    assert m.num_target_rows_inserted == 1  # the NULL source row
    out = dta.read_table(tmp_table_path)
    vals = sorted(out.column("v").to_pylist())
    assert vals == [20.0, 30.0, 88.0, 99.0]


def test_merge_duplicate_assignment_differing_case_raises(target_path):
    """Two explicit SET assignments differing only in case are one
    duplicate assignment (the reference analyzer rejects them), not a
    silent last-wins collapse."""
    table = Table.for_path(target_path)
    src = _source([3], [300.0])
    m = (
        merge(table, src, on=col("target.id") == col("source.id"))
        .when_matched_update(set={"value": lit(1.0), "VALUE": lit(2.0)})
    )
    with pytest.raises(DeltaError, match="duplicate assignment"):
        m.execute()
    # analysis-time error: raised even when no row reaches the clause
    src_nomatch = _source([999], [1.0])
    m = (
        merge(Table.for_path(target_path), src_nomatch,
              on=col("target.id") == col("source.id"))
        .when_matched_update(set={"value": lit(1.0), "VALUE": lit(2.0)})
    )
    with pytest.raises(DeltaError, match="duplicate assignment"):
        m.execute()


def test_merge_clause_validation(tmp_table_path):
    """Reference analysis rules: MERGE without WHEN clauses, and
    non-last clauses omitting their condition (which would shadow
    later clauses) are rejected with their catalog classes."""
    import pyarrow as pa
    import pytest

    import delta_tpu.api as dta
    from delta_tpu.commands.merge import merge
    from delta_tpu.errors import DeltaError, error_info
    from delta_tpu.expressions.tree import col
    from delta_tpu.table import Table

    dta.write_table(tmp_table_path, pa.table({"id": [1, 2]}))
    t = Table.for_path(tmp_table_path)
    src = pa.table({"id": [2, 3]})
    on = col("target.id") == col("source.id")

    with pytest.raises(DeltaError) as ei:
        merge(t, src, on).execute()
    assert error_info(ei.value)["errorClass"] == "DELTA_MERGE_MISSING_WHEN"

    b = (merge(t, src, on)
         .when_matched_delete()            # unconditional, NOT last
         .when_matched_update_all())
    with pytest.raises(DeltaError) as ei:
        b.execute()
    assert error_info(ei.value)["errorClass"] == \
        "DELTA_NON_LAST_MATCHED_CLAUSE_OMIT_CONDITION"

    b = (merge(t, src, on)
         .when_not_matched_insert_all()
         .when_not_matched_insert(values={"id": col("source.id")}))
    with pytest.raises(DeltaError) as ei:
        b.execute()
    assert error_info(ei.value)["errorClass"] == \
        "DELTA_NON_LAST_NOT_MATCHED_CLAUSE_OMIT_CONDITION"

    b = (merge(t, src, on)
         .when_not_matched_by_source_delete()
         .when_not_matched_by_source_update(set={"id": col("target.id")}))
    with pytest.raises(DeltaError) as ei:
        b.execute()
    assert error_info(ei.value)["errorClass"] == \
        "DELTA_NON_LAST_NOT_MATCHED_BY_SOURCE_CLAUSE_OMIT_CONDITION"

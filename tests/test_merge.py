import numpy as np
import pyarrow as pa
import pytest

import delta_tpu.api as dta
from delta_tpu.commands.merge import MergeCardinalityError, merge
from delta_tpu.expressions import col, lit
from delta_tpu.table import Table


@pytest.fixture
def target_path(tmp_table_path):
    data = pa.table(
        {
            "id": pa.array([1, 2, 3, 4, 5], pa.int64()),
            "value": pa.array([10.0, 20.0, 30.0, 40.0, 50.0]),
            "status": pa.array(["a", "a", "a", "a", "a"]),
        }
    )
    dta.write_table(tmp_table_path, data)
    return tmp_table_path


def _source(ids, values, ops=None):
    cols = {
        "id": pa.array(ids, pa.int64()),
        "value": pa.array(values, pa.float64()),
    }
    if ops is not None:
        cols["op"] = pa.array(ops, pa.string())
    return pa.table(cols)


def test_merge_upsert(target_path):
    table = Table.for_path(target_path)
    src = _source([3, 4, 6, 7], [300.0, 400.0, 600.0, 700.0])
    m = (
        merge(table, src, on=col("target.id") == col("source.id"))
        .when_matched_update(set={"value": col("source.value")})
        .when_not_matched_insert(
            values={"id": col("source.id"), "value": col("source.value"),
                    "status": lit("new")}
        )
        .execute()
    )
    assert m.num_target_rows_updated == 2
    assert m.num_target_rows_inserted == 2
    assert m.num_target_rows_copied == 3
    out = dta.read_table(target_path).sort_by("id")
    assert out.column("id").to_pylist() == [1, 2, 3, 4, 5, 6, 7]
    assert out.column("value").to_pylist() == [10.0, 20.0, 300.0, 400.0, 50.0, 600.0, 700.0]
    st = out.column("status").to_pylist()
    assert st[5] == "new" and st[6] == "new"


def test_merge_matched_delete_with_condition(target_path):
    table = Table.for_path(target_path)
    src = _source([1, 2, 3], [0.0, 0.0, 0.0], ops=["del", "keep", "del"])
    m = (
        merge(table, src, on=col("target.id") == col("source.id"))
        .when_matched_delete(condition=col("source.op") == lit("del"))
        .when_matched_update(set={"value": col("source.value")})
        .execute()
    )
    assert m.num_target_rows_deleted == 2
    assert m.num_target_rows_updated == 1
    out = dta.read_table(target_path).sort_by("id")
    assert out.column("id").to_pylist() == [2, 4, 5]
    assert out.column("value").to_pylist()[0] == 0.0


def test_merge_clause_order_first_wins(target_path):
    table = Table.for_path(target_path)
    src = _source([1], [99.0])
    (
        merge(table, src, on=col("target.id") == col("source.id"))
        .when_matched_update(set={"value": lit(111.0)},
                             condition=col("target.value") < lit(15.0))
        .when_matched_update(set={"value": lit(222.0)})
        .execute()
    )
    out = dta.read_table(target_path).sort_by("id")
    assert out.column("value").to_pylist()[0] == 111.0


def test_merge_not_matched_by_source_delete(target_path):
    table = Table.for_path(target_path)
    src = _source([1, 2], [0.0, 0.0])
    m = (
        merge(table, src, on=col("target.id") == col("source.id"))
        .when_matched_update(set={"value": col("source.value")})
        .when_not_matched_by_source_delete()
        .execute()
    )
    assert m.num_target_rows_deleted == 3
    out = dta.read_table(target_path).sort_by("id")
    assert out.column("id").to_pylist() == [1, 2]


def test_merge_cardinality_violation(target_path):
    table = Table.for_path(target_path)
    src = _source([3, 3], [1.0, 2.0])
    with pytest.raises(MergeCardinalityError):
        (
            merge(table, src, on=col("target.id") == col("source.id"))
            .when_matched_update(set={"value": col("source.value")})
            .execute()
        )


def test_merge_insert_all(target_path):
    table = Table.for_path(target_path)
    src = pa.table(
        {
            "id": pa.array([8], pa.int64()),
            "value": pa.array([80.0]),
            "status": pa.array(["s"]),
        }
    )
    (
        merge(table, src, on=col("target.id") == col("source.id"))
        .when_not_matched_insert_all()
        .execute()
    )
    out = dta.read_table(target_path).sort_by("id")
    assert out.column("id").to_pylist() == [1, 2, 3, 4, 5, 8]
    assert out.column("status").to_pylist()[-1] == "s"


def test_merge_residual_condition(target_path):
    table = Table.for_path(target_path)
    src = _source([1, 2], [100.0, 200.0])
    (
        merge(
            table, src,
            on=(col("target.id") == col("source.id"))
            & (col("source.value") > lit(150.0)),
        )
        .when_matched_update(set={"value": col("source.value")})
        .execute()
    )
    out = dta.read_table(target_path).sort_by("id")
    vals = out.column("value").to_pylist()
    assert vals[0] == 10.0      # id=1 pair filtered out by residual
    assert vals[1] == 200.0     # id=2 updated

"""Graceful degradation under log corruption: unreadable checkpoints
fall back to an earlier checkpoint or pure JSON replay, a torn trailing
commit serves the last intact version, and a lying `.crc` checksum is
quarantined by reseeding — all without failing the read."""

import glob
import json
import os

import pytest

from delta_tpu import obs
from delta_tpu.engine.host import HostEngine
from delta_tpu.errors import LogCorruptedError, TornCommitError
from delta_tpu.models.actions import AddFile
from delta_tpu.models.schema import INTEGER, StructField, StructType
from delta_tpu.replay.columnar import clear_parse_cache
from delta_tpu.table import Table


@pytest.fixture(autouse=True)
def _fresh_parse_cache():
    clear_parse_cache()
    yield
    clear_parse_cache()


def _make_table(path) -> Table:
    t = Table.for_path(str(path), HostEngine())
    t.create_transaction_builder().with_schema(
        StructType([StructField("x", INTEGER)])).build().commit()
    return t


def _commit(t: Table, i: int):
    txn = t.start_transaction()
    txn.add_file(AddFile(
        path=f"p{i}.parquet", partitionValues={}, size=100 + i,
        modificationTime=1000 + i, dataChange=True,
        stats=json.dumps({"numRecords": i})))
    txn.commit()


def _cold(path) -> Table:
    clear_parse_cache()
    return Table.for_path(str(path), HostEngine())


def _log_file(path, pattern):
    files = sorted(glob.glob(os.path.join(str(path), "_delta_log",
                                          pattern)))
    assert files, f"no {pattern} under {path}"
    return files


def _expected_paths(n):
    return sorted(f"p{i}.parquet" for i in range(n))


def _live_paths(snap):
    st = snap.state
    import numpy as np

    mask = np.asarray(st.live_mask)
    return sorted(p for p, m in zip(
        st.file_actions.column("path").to_pylist(), mask.tolist()) if m)


# ----------------------------------------------------- checkpoint parts


def test_truncated_checkpoint_falls_back_to_json(tmp_path):
    t = _make_table(tmp_path)
    for i in range(4):
        _commit(t, i)
    t.checkpoint()
    _commit(t, 4)

    cp = _log_file(tmp_path, "*.checkpoint.parquet")[0]
    with open(cp, "rb") as f:
        data = f.read()
    with open(cp, "wb") as f:
        f.write(data[: len(data) // 2])

    c0 = obs.counter("snapshot.checkpoint_fallbacks").value
    snap = _cold(tmp_path).latest_snapshot()
    assert snap.version == 5
    assert _live_paths(snap) == _expected_paths(5)
    assert obs.counter("snapshot.checkpoint_fallbacks").value == c0 + 1


def test_garbled_checkpoint_falls_back_to_previous_checkpoint(tmp_path):
    t = _make_table(tmp_path)
    for i in range(2):
        _commit(t, i)
    t.checkpoint()  # v2 — the good one
    for i in range(2, 4):
        _commit(t, i)
    t.checkpoint()  # v4 — will be garbled
    _commit(t, 4)

    cps = _log_file(tmp_path, "*.checkpoint.parquet")
    assert len(cps) == 2
    with open(cps[-1], "wb") as f:
        f.write(b"\x89not-a-parquet-file" * 64)

    c0 = obs.counter("snapshot.checkpoint_fallbacks").value
    snap = _cold(tmp_path).latest_snapshot()
    assert snap.version == 5
    assert _live_paths(snap) == _expected_paths(5)
    assert obs.counter("snapshot.checkpoint_fallbacks").value == c0 + 1
    # the fallback segment is anchored at the surviving v2 checkpoint
    assert snap.log_segment.checkpoint_version == 2


def test_missing_multipart_part_falls_back(tmp_path):
    from delta_tpu.config import settings
    from delta_tpu.log.checkpointer import write_checkpoint

    t = _make_table(tmp_path)
    for i in range(4):
        _commit(t, i)
    saved = settings.checkpoint_part_size
    settings.checkpoint_part_size = 2
    try:
        write_checkpoint(t.engine, t.latest_snapshot(), policy="classic")
    finally:
        settings.checkpoint_part_size = saved
    _commit(t, 4)

    parts = _log_file(tmp_path, "*.checkpoint.0*.parquet")
    assert len(parts) > 1, "multipart checkpoint did not split"
    os.remove(parts[0])

    # the incomplete checkpoint is rejected at listing time: the stale
    # `_last_checkpoint` hint is discarded and the full listing replays
    # from the JSON commits alone
    c0 = obs.counter("log.hint_discarded").value
    snap = _cold(tmp_path).latest_snapshot()
    assert snap.version == 5
    assert _live_paths(snap) == _expected_paths(5)
    assert snap.log_segment.checkpoint_version is None
    assert obs.counter("log.hint_discarded").value == c0 + 1


# ------------------------------------------------------- torn commits


def test_torn_trailing_commit_serves_previous_version(tmp_path):
    t = _make_table(tmp_path)
    for i in range(3):
        _commit(t, i)

    tip = _log_file(tmp_path, "*.json")[-1]
    assert tip.endswith("00000000000000000003.json")
    with open(tip, "rb") as f:
        data = f.read()
    torn = data.rstrip(b"\n")
    with open(tip, "wb") as f:
        f.write(torn[: len(torn) - len(torn) // 3])

    t0 = obs.counter("log.torn_commits").value
    f0 = obs.counter("snapshot.torn_commit_fallbacks").value
    snap = _cold(tmp_path).latest_snapshot()
    state = snap.state
    assert state.version == 2
    assert snap.version == 2
    assert _live_paths(snap) == _expected_paths(2)
    assert obs.counter("log.torn_commits").value > t0
    assert obs.counter("snapshot.torn_commit_fallbacks").value == f0 + 1


def test_torn_midlog_commit_is_plain_corruption(tmp_path):
    t = _make_table(tmp_path)
    for i in range(3):
        _commit(t, i)

    mid = _log_file(tmp_path, "*.json")[2]
    assert mid.endswith("00000000000000000002.json")
    with open(mid, "rb") as f:
        data = f.read()
    torn = data.rstrip(b"\n")
    with open(mid, "wb") as f:
        f.write(torn[: len(torn) - len(torn) // 3])

    with pytest.raises(LogCorruptedError) as ei:
        _cold(tmp_path).latest_snapshot().state
    # mid-log damage is NOT the recoverable torn-tip shape
    assert not isinstance(ei.value, TornCommitError)


def test_torn_commit_error_carries_version(tmp_path):
    from delta_tpu.replay.columnar import parse_commit_batch

    good = b'{"commitInfo": {"operation": "WRITE"}}\n'
    with pytest.raises(TornCommitError) as ei:
        parse_commit_batch([(0, good), (1, good + b'{"add": {"pa')])
    assert ei.value.context["version"] == 1
    assert ei.value.error_class == "DELTA_TORN_COMMIT"


# ------------------------------------------------------------- checksum


def test_crc_mismatch_quarantined_and_reseeded(tmp_path):
    t = _make_table(tmp_path)
    for i in range(3):
        _commit(t, i)
    t.checkpoint()  # reseeds the .crc chain at v3

    crcs = _log_file(tmp_path, "*.crc")
    crc_path = crcs[-1]
    doc = json.loads(open(crc_path).read())
    doc["numFiles"] = doc["numFiles"] + 7
    doc["tableSizeBytes"] = doc["tableSizeBytes"] + 999
    with open(crc_path, "w") as f:
        f.write(json.dumps(doc))

    q0 = obs.counter("snapshot.crc_quarantined").value
    snap = _cold(tmp_path).latest_snapshot()
    assert _live_paths(snap) == _expected_paths(3)  # read never fails
    assert obs.counter("snapshot.crc_quarantined").value == q0 + 1

    # the lying checksum was reseeded from the replayed state
    reseeded = json.loads(open(crc_path).read())
    assert reseeded["numFiles"] == snap.state.num_files
    assert reseeded["tableSizeBytes"] == snap.state.size_in_bytes

    # a second cold read sees a healthy chain — no further quarantine
    snap2 = _cold(tmp_path).latest_snapshot()
    assert _live_paths(snap2) == _expected_paths(3)
    assert obs.counter("snapshot.crc_quarantined").value == q0 + 1


# --------------------------------------------- seeded read corruption


def test_default_corrupt_pred_scope():
    """Only payloads the fallback ladder can absorb are eligible: a
    corrupt commit .json is unrecoverable data loss, so chaos never
    touches it."""
    from delta_tpu.resilience.chaos import _default_corrupt_pred as pred

    log = "mem://t/_delta_log"
    assert pred(f"{log}/00000000000000000003.checkpoint.parquet")
    assert pred(f"{log}/00000000000000000009.checkpoint."
                "0000000001.0000000004.parquet")
    assert pred(f"{log}/00000000000000000003.crc")
    assert not pred(f"{log}/00000000000000000003.json")
    assert not pred(f"{log}/_last_checkpoint")


def test_draw_flip_offsets_seeded_and_tail_windowed():
    from delta_tpu.resilience.chaos import ChaosSchedule

    a = ChaosSchedule(19).draw_flip_offsets(4096)
    b = ChaosSchedule(19).draw_flip_offsets(4096)
    assert a == b and a  # same seed, same damage
    for off, bit in a:
        assert 4096 - 16 <= off < 4096  # footer/digest window
        assert 0 <= bit < 8
    # payloads smaller than the window stay in bounds
    for off, _bit in ChaosSchedule(19).draw_flip_offsets(5):
        assert 0 <= off < 5


def _corrupting_engine(seed, rate):
    from delta_tpu.engine.host import HostEngine as _Host
    from delta_tpu.resilience import ChaosSchedule, ChaosStore
    from delta_tpu.storage.logstore import InMemoryLogStore

    store = ChaosStore(InMemoryLogStore(),
                       ChaosSchedule(seed, error_rate=0.0,
                                     corrupt_read_rate=rate),
                       sleep=lambda s: None)
    return _Host(store_resolver=lambda path: store), store


def test_read_corruption_absorbed_by_fallback_ladder():
    """Every checkpoint/crc read returns a damaged payload, yet a cold
    read still serves the exact table: the ladder (crc quarantine,
    checkpoint fallback to JSON replay) absorbs validation failures the
    transport never sees."""
    import delta_tpu.api as dta
    import pyarrow as pa

    eng, store = _corrupting_engine(seed=23, rate=1.0)
    path = "memory://corrupt-soak/tbl"

    store.enabled = False  # build the table cleanly
    dta.write_table(path, pa.table({"x": list(range(10))}), engine=eng)
    for i in range(3):
        dta.write_table(path, pa.table({"x": [100 + i]}), engine=eng,
                        mode="append")
    t = Table.for_path(path, eng)
    t.checkpoint()
    dta.write_table(path, pa.table({"x": [999]}), engine=eng,
                    mode="append")
    expected = sorted(list(range(10)) + [100, 101, 102, 999])

    store.enabled = True
    c0 = obs.counter("chaos.read_corruptions").value
    f0 = obs.counter("snapshot.checkpoint_fallbacks").value
    clear_parse_cache()
    got = sorted(dta.read_table(path, engine=eng)
                 .column("x").to_pylist())
    assert got == expected  # read never fails, rows exact
    assert store.fault_counts.get("corrupt_read", 0) > 0
    assert obs.counter("chaos.read_corruptions").value > c0
    # the damaged checkpoint was abandoned for JSON replay
    assert obs.counter("snapshot.checkpoint_fallbacks").value > f0

    store.enabled = False  # verification read, chaos off
    clear_parse_cache()
    clean = sorted(dta.read_table(path, engine=eng)
                   .column("x").to_pylist())
    assert clean == expected


def test_read_corruption_never_touches_commit_json():
    """Commit deltas are outside the damage scope even at rate 1.0:
    every corrupted payload is a checkpoint artifact or a .crc
    sidecar (both absorbable), never a .json commit (which would be
    unrecoverable data loss)."""
    import delta_tpu.api as dta
    import pyarrow as pa

    eng, store = _corrupting_engine(seed=29, rate=1.0)
    path = "memory://corrupt-json/tbl"
    store.enabled = False
    dta.write_table(path, pa.table({"x": [1, 2, 3]}), engine=eng)
    store.enabled = True
    clear_parse_cache()
    assert sorted(dta.read_table(path, engine=eng)
                  .column("x").to_pylist()) == [1, 2, 3]
    for kind, _op, hit in store.fault_log:
        if kind != "corrupt_read":
            continue
        name = hit.rpartition("/")[2]
        assert ".checkpoint" in name or name.endswith(".crc"), hit
        assert not name.endswith(".json"), hit

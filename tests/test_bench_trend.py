"""delta-bench-trend: noise-banded regression verdicts over BENCH_r*
artifacts, metric-direction heuristics, conditions backfill, and the
heterogeneous artifact formats (tail JSON lines vs metrics list)."""

import json

import pytest

from delta_tpu.obs import bench_trend
from delta_tpu.obs.device import CONDITIONS_UNKNOWN


def _write_runs(tmp_path, series, metric="load_actions_per_sec",
                conditions="cond-a"):
    """Write BENCH_r01..rNN artifacts in the modern (metrics-list)
    shape; `series` is [(value, conditions?)...] — a bare number uses
    the default conditions."""
    paths = []
    for i, point in enumerate(series, start=1):
        value, cond = point if isinstance(point, tuple) else (point,
                                                              conditions)
        p = tmp_path / f"BENCH_r{i:02d}.json"
        p.write_text(json.dumps({
            "n": i,
            "conditions": cond,
            "metrics": [{"metric": metric, "value": value, "unit": "x"}],
        }, indent=1))
        paths.append(str(p))
    return paths


def _verdict(tmp_path, series, metric="load_actions_per_sec", **kw):
    runs = bench_trend.load_bench_runs(
        _write_runs(tmp_path, series, metric=metric))
    [v] = bench_trend.trend_verdicts(runs, **kw)
    return v


# ----------------------------------------------------- verdicts -------------

def test_synthetic_regression_is_flagged(tmp_path):
    # higher-is-better throughput drops 40% against a tight history
    v = _verdict(tmp_path, [100.0, 102.0, 98.0, 101.0, 60.0])
    assert v["verdict"] == "regressed"
    assert v["comparable_points"] == 4
    assert v["delta_pct"] < -30


def test_noise_within_band_is_stable(tmp_path):
    v = _verdict(tmp_path, [100.0, 102.0, 98.0, 101.0, 104.0])
    assert v["verdict"] == "stable"


def test_improvement_outside_band(tmp_path):
    v = _verdict(tmp_path, [100.0, 102.0, 98.0, 101.0, 150.0])
    assert v["verdict"] == "improved"


def test_band_widens_with_noisy_history(tmp_path):
    """A history that itself swings 30% must not flag a 20% move: the
    band is 2x the MAD, floored at min_band_pct — never tighter."""
    v = _verdict(tmp_path, [100.0, 140.0, 70.0, 125.0, 80.0])
    assert v["band_pct"] > 30
    assert v["verdict"] == "stable"


def test_lower_is_better_direction(tmp_path):
    up = _verdict(tmp_path, [10.0, 11.0, 9.0, 10.0, 20.0],
                  metric="trace_overhead_pct")
    assert up["verdict"] == "regressed"  # overhead going UP regresses
    down = _verdict(tmp_path, [10.0, 11.0, 9.0, 10.0, 5.0],
                    metric="trace_overhead_pct")
    assert down["verdict"] == "improved"


def test_insufficient_history(tmp_path):
    v = _verdict(tmp_path, [100.0, 101.0, 99.0])  # 2 comparable points
    assert v["verdict"] == "insufficient-history"
    assert "delta_pct" not in v


def test_unknown_direction_refuses_verdict(tmp_path):
    v = _verdict(tmp_path, [1.0, 1.0, 1.0, 9.0], metric="mystery_number")
    assert v["verdict"] == "unknown-direction"


def test_different_fingerprints_never_compare(tmp_path):
    """A TPU capture is not a baseline for a CPU capture: history
    points under other conditions drop out of the comparison."""
    series = [(100.0, "cpu"), (101.0, "cpu"), (99.0, "cpu"),
              (100.0, "cpu"), (500.0, "tpu")]
    v = _verdict(tmp_path, series)
    assert v["fingerprint"] == "tpu"
    assert v["comparable_points"] == 0
    assert v["verdict"] == "insufficient-history"


def test_zero_median_history(tmp_path):
    flat = _verdict(tmp_path, [0.0, 0.0, 0.0, 0.0],
                    metric="analyzer_findings_total")
    assert flat["verdict"] == "stable"
    spike = _verdict(tmp_path, [0.0, 0.0, 0.0, 3.0],
                     metric="analyzer_findings_total")
    assert spike["verdict"] == "regressed"


# ------------------------------------------------ direction rules -----------

@pytest.mark.parametrize("name,expected", [
    ("e2e_snapshot_load_actions_per_sec", +1),
    ("device_json_parse_gbps", +1),
    ("replay_kernel_speedup_large", +1),
    ("incremental_checkpoint_reuse_pct", +1),     # explicit: a hit rate
    ("trace_overhead_pct", -1),
    ("device_obs_overhead_pct", -1),
    ("cold_first_commit_seconds", -1),
    ("serve_p99_ms_chaos", -1),
    ("analyzer_findings_total", -1),
    ("mystery_number", 0),
])
def test_metric_direction(name, expected):
    assert bench_trend.metric_direction(name) == expected


# ------------------------------------------- artifact heterogeneity ---------

def test_extract_metrics_precedence_and_tail_lines(tmp_path):
    """Legacy artifacts embed metric JSON lines in the captured tail;
    the parsed record and the modern metrics list override them."""
    p = tmp_path / "BENCH_r03.json"
    p.write_text(json.dumps({
        "n": 3,
        "tail": 'noise line\n{"metric": "a_per_sec", "value": 1}\n'
                '{"metric": "b_per_sec", "value": 5}\nnot json {"metric"',
        "parsed": {"metric": "a_per_sec", "value": 2},
        "metrics": [{"metric": "a_per_sec", "value": 3}],
    }))
    [run] = bench_trend.load_bench_runs([str(p)])
    assert run["n"] == 3
    assert run["metrics"] == {"a_per_sec": 3.0, "b_per_sec": 5.0}
    # no conditions key -> the pre-schema sentinel group
    assert run["fingerprint"] == CONDITIONS_UNKNOWN


def test_load_skips_unreadable(tmp_path):
    good = _write_runs(tmp_path, [1.0])
    bad = tmp_path / "BENCH_r09.json"
    bad.write_text("{truncated")
    runs = bench_trend.load_bench_runs(good + [str(bad),
                                               str(tmp_path / "nope.json")])
    assert len(runs) == 1


# ------------------------------------------------------ backfill ------------

def test_backfill_stamps_and_is_idempotent(tmp_path):
    legacy = tmp_path / "BENCH_r01.json"
    legacy.write_text(json.dumps({"n": 1, "parsed": {"metric": "m_per_sec",
                                                     "value": 1}}, indent=2)
                      + "\n")
    modern = tmp_path / "BENCH_r02.json"
    modern.write_text(json.dumps({
        "n": 2, "conditions": {"schema": "v1"},
        "metrics": [{"metric": "m_per_sec", "value": 2}]}, indent=1))
    paths = [str(legacy), str(modern)]

    assert bench_trend.backfill_conditions(paths) == 1
    stamped = json.loads(legacy.read_text())
    assert stamped["conditions"] == CONDITIONS_UNKNOWN
    # detected indent preserved (artifact was written with indent=2)
    assert '\n  "n"' in legacy.read_text()
    # artifacts that already carry conditions are untouched
    assert json.loads(modern.read_text())["conditions"] == {"schema": "v1"}

    before = legacy.read_text()
    assert bench_trend.backfill_conditions(paths) == 0  # second run: no-op
    assert legacy.read_text() == before


# ----------------------------------------------------------- CLI ------------

def test_cli_text_json_and_fail_on_regress(tmp_path, capsys):
    _write_runs(tmp_path, [100.0, 101.0, 99.0, 100.0, 50.0])
    root = ["--root", str(tmp_path)]

    assert bench_trend.main(root) == 0
    out = capsys.readouterr().out
    assert "load_actions_per_sec" in out and "regressed" in out

    assert bench_trend.main(root + ["--fail-on-regress"]) == 1
    capsys.readouterr()

    assert bench_trend.main(root + ["--json"]) == 0
    [v] = json.loads(capsys.readouterr().out)
    assert v["verdict"] == "regressed" and v["latest_run"] == 5

    assert bench_trend.main(["--root", str(tmp_path / "empty")]) == 2


def test_cli_backfill_and_metric_filter(tmp_path, capsys):
    p = tmp_path / "BENCH_r01.json"
    p.write_text(json.dumps({"n": 1, "metrics": [
        {"metric": "a_per_sec", "value": 1},
        {"metric": "b_per_sec", "value": 2}]}))
    assert bench_trend.main(["--root", str(tmp_path), "--backfill"]) == 0
    assert "backfilled 1 of 1" in capsys.readouterr().out
    assert json.loads(p.read_text())["conditions"] == CONDITIONS_UNKNOWN

    assert bench_trend.main(["--root", str(tmp_path),
                             "--metric", "a_per_sec"]) == 0
    out = capsys.readouterr().out
    assert "a_per_sec" in out and "b_per_sec" not in out


def test_repo_artifacts_produce_verdicts():
    """Acceptance: the tool runs over the repo's own BENCH_r01..r06 and
    reaches a banded verdict for the cross-revision headline metric."""
    import os

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    paths = bench_trend._find_artifacts(root, "BENCH_r*.json")
    assert len(paths) >= 6
    runs = bench_trend.load_bench_runs(paths)
    assert all(r["fingerprint"] == CONDITIONS_UNKNOWN for r in runs)
    verdicts = bench_trend.trend_verdicts(
        runs, metrics=["e2e_snapshot_load_actions_per_sec"])
    [v] = verdicts
    assert v["comparable_points"] >= 3
    assert v["verdict"] in ("stable", "improved", "regressed")

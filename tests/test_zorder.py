import numpy as np
import jax.numpy as jnp

from delta_tpu.ops.zorder import (
    curve_order,
    hilbert_key,
    interleave_bits,
    range_rank,
    zorder_sort_indices,
)


def _interleave_ref(cols, n_bits=32):
    """Bit-level reference: round-robin MSB-first interleave."""
    k = len(cols)
    n = len(cols[0])
    total = k * n_bits
    n_words = max(1, -(-total // 32))
    out = np.zeros((n_words, n), dtype=np.uint32)
    for row in range(n):
        for g in range(total):
            c = g % k
            s = n_bits - 1 - g // k
            bit = (int(cols[c][row]) >> s) & 1
            w, wb = divmod(g, 32)
            out[w, row] |= np.uint32(bit << (31 - wb))
    return out


def test_interleave_matches_reference():
    rng = np.random.default_rng(0)
    cols = [rng.integers(0, 2**32, 20, dtype=np.uint32) for _ in range(3)]
    got = np.asarray(interleave_bits([jnp.asarray(c) for c in cols]))
    ref = _interleave_ref(cols)
    np.testing.assert_array_equal(got, ref)


def test_interleave_two_cols_known_values():
    # x=0b11, y=0b00 -> interleaved MSBs ... x bit then y bit
    x = np.array([0b11], dtype=np.uint32)
    y = np.array([0b00], dtype=np.uint32)
    got = np.asarray(interleave_bits([jnp.asarray(x), jnp.asarray(y)]))
    ref = _interleave_ref([x, y])
    np.testing.assert_array_equal(got, ref)


def test_range_rank():
    v = jnp.asarray(np.array([30, 10, 20, 10], dtype=np.uint32))
    r = np.asarray(range_rank(v))
    assert r[0] == 3
    assert sorted(r.tolist()) == [0, 1, 2, 3]


def test_curve_order_is_permutation():
    rng = np.random.default_rng(1)
    cols = [rng.integers(0, 2**32, 100, dtype=np.uint32) for _ in range(2)]
    keys = interleave_bits([jnp.asarray(c) for c in cols])
    perm = np.asarray(curve_order(keys))
    assert sorted(perm.tolist()) == list(range(100))


def test_zorder_locality():
    """Z-ordering a 2-D grid must colocate spatial neighbors better than
    row-major order: measure the mean Chebyshev jump between consecutive
    rows — for a Z-curve it should be far below the row-major worst case."""
    side = 32
    xs, ys = np.meshgrid(np.arange(side), np.arange(side))
    x = xs.ravel().astype(np.int64)
    y = ys.ravel().astype(np.int64)
    perm = zorder_sort_indices([x, y], curve="zorder")
    px, py = x[perm], y[perm]
    jumps = np.maximum(np.abs(np.diff(px)), np.abs(np.diff(py)))
    assert jumps.mean() < 3.0


def test_hilbert_locality_beats_zorder():
    side = 32
    xs, ys = np.meshgrid(np.arange(side), np.arange(side))
    x = xs.ravel().astype(np.int64)
    y = ys.ravel().astype(np.int64)

    def mean_jump(perm):
        px, py = x[perm], y[perm]
        return float(np.maximum(np.abs(np.diff(px)), np.abs(np.diff(py))).mean())

    z = mean_jump(zorder_sort_indices([x, y], curve="zorder"))
    h = mean_jump(zorder_sort_indices([x, y], curve="hilbert"))
    # Hilbert: every step is adjacent (jump == 1) on a perfect grid
    assert h <= 1.0 + 1e-9
    assert h < z


def test_hilbert_key_is_bijection():
    side = 16
    xs, ys = np.meshgrid(np.arange(side), np.arange(side))
    coords = [jnp.asarray(xs.ravel().astype(np.uint32)),
              jnp.asarray(ys.ravel().astype(np.uint32))]
    keys = np.asarray(hilbert_key(coords, n_bits=4))
    flat = keys[0].astype(np.uint64)
    assert len(np.unique(flat)) == side * side


def test_sortable_u32_strings_and_floats():
    strs = np.array(["b", "a", "c"], dtype=object)
    perm = zorder_sort_indices([strs], curve="zorder")
    assert strs[perm].tolist() == ["a", "b", "c"]
    floats = np.array([3.5, -1.0, 0.0, -np.inf])
    perm = zorder_sort_indices([floats], curve="zorder")
    assert floats[perm].tolist() == sorted(floats.tolist())

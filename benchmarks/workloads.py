"""Benchmark workloads.

- `replay`: the north-star — snapshot state reconstruction over a
  synthetic `_delta_log` (BASELINE.md config 2: 100k commits / 10M adds
  at `--scale full`; smaller presets for CI). Compares the sequential
  reference replay, the single-device kernel, and (where >1 device) the
  sharded path, plus end-to-end table load including JSON parse.
- `checkpoint`: checkpoint write throughput from a reconstructed state
  (config 2's GB/s half).
- `optimize`: bin-packing compaction + ZORDER rewrite (configs 3/4).
- `merge`: upsert MERGE throughput (reference MergeBenchmark role).
- `streaming`: micro-batch ingest + per-batch stats (config 5).
"""

from __future__ import annotations

import json
import os
import shutil
import sys
import time

import numpy as np
import pyarrow as pa

from benchmarks.harness import Benchmark, QueryResult

SCALES = {
    "smoke": dict(commits=50, files_per_commit=20, rows=5_000),
    "small": dict(commits=1_000, files_per_commit=100, rows=50_000),
    "medium": dict(commits=10_000, files_per_commit=100, rows=200_000),
    "large": dict(commits=30_000, files_per_commit=100, rows=500_000),
    "full": dict(commits=100_000, files_per_commit=100, rows=1_000_000),
}


def synth_delta_log(path: str, commits: int, files_per_commit: int,
                    remove_fraction: float = 0.2) -> None:
    """Write a synthetic `_delta_log` directly (no data files — replay
    only touches the log)."""
    rng = np.random.default_rng(0)
    log = os.path.join(path, "_delta_log")
    os.makedirs(log, exist_ok=True)
    protocol = '{"protocol":{"minReaderVersion":1,"minWriterVersion":2}}'
    metadata = json.dumps({
        "metaData": {
            "id": "bench", "format": {"provider": "parquet", "options": {}},
            "schemaString": '{"type":"struct","fields":[{"name":"x","type":"long","nullable":true,"metadata":{}}]}',
            "partitionColumns": [], "configuration": {},
        }
    })
    alive: list = []
    fid = 0
    for v in range(commits):
        lines = []
        if v == 0:
            lines += [protocol, metadata]
        n_rm = int(files_per_commit * remove_fraction)
        if alive and n_rm:
            for _ in range(min(n_rm, len(alive))):
                p = alive.pop(rng.integers(0, len(alive)))
                lines.append(json.dumps({
                    "remove": {"path": p, "deletionTimestamp": v, "dataChange": True}
                }))
        for _ in range(files_per_commit - n_rm):
            p = f"part-{fid:010d}.parquet"
            fid += 1
            alive.append(p)
            stats = json.dumps({"numRecords": 1000,
                                "minValues": {"x": int(fid) * 1000},
                                "maxValues": {"x": int(fid + 1) * 1000},
                                "nullCount": {"x": 0}})
            lines.append(json.dumps({
                "add": {"path": p, "partitionValues": {}, "size": 1 << 20,
                        "modificationTime": v, "dataChange": True,
                        "stats": stats}
            }))
        with open(os.path.join(log, f"{v:020d}.json"), "w") as f:
            f.write("\n".join(lines) + "\n")


class ReplayBenchmark(Benchmark):
    name = "replay"

    def run(self):
        from delta_tpu.engine.host import HostEngine
        from delta_tpu.engine.tpu import TpuEngine
        from delta_tpu.replay.columnar import columnarize_log_segment
        from delta_tpu.replay.state import compute_masks_device, compute_masks_host
        from delta_tpu.log.segment import build_log_segment
        from delta_tpu.table import Table

        cfg = SCALES[self.scale]
        path = os.path.join(self.workdir, f"replay_{self.scale}")
        if not os.path.exists(os.path.join(path, "_delta_log")):
            print(f"  generating {cfg['commits']} commits...", end=" ", flush=True)
            t0 = time.perf_counter()
            synth_delta_log(path, cfg["commits"], cfg["files_per_commit"])
            print(f"{time.perf_counter() - t0:.1f}s")

        engine = TpuEngine()
        with self.timed("list+segment"):
            segment = build_log_segment(engine.fs, os.path.join(path, "_delta_log"))
        with self.timed("columnarize(parse json)"):
            columnar = columnarize_log_segment(engine, segment)
        n = columnar.num_actions

        with self.timed("replay-host-dict", extra={"actions": n}):
            live_h, _ = compute_masks_host(columnar)
        # device (includes key factorization + transfers)
        with self.timed("replay-device-e2e", 0):
            live_d, _ = compute_masks_device(columnar)
        with self.timed("replay-device-e2e", 1):
            live_d, _ = compute_masks_device(columnar)
        assert live_h.sum() == live_d.sum()

        host_ms = next(r.duration_ms for r in self.report.results
                       if r.name == "replay-host-dict")
        dev_ms = min(r.duration_ms for r in self.report.results
                     if r.name == "replay-device-e2e")
        self.metric("replay_actions_per_sec_host", n / host_ms * 1000, "actions/s")
        self.metric("replay_actions_per_sec_device", n / dev_ms * 1000, "actions/s",
                    vs_host=round(host_ms / dev_ms, 2))

        # full table load end-to-end on both engines
        for label, eng in (("host", HostEngine()), ("tpu", TpuEngine())):
            with self.timed(f"full-load-{label}"):
                snap = Table.for_path(path, eng).latest_snapshot()
                _ = snap.num_files
        return self.report


class CheckpointBenchmark(Benchmark):
    name = "checkpoint"

    def run(self):
        from delta_tpu.engine.tpu import TpuEngine
        from delta_tpu.log.checkpointer import write_checkpoint
        from delta_tpu.table import Table

        cfg = SCALES[self.scale]
        path = os.path.join(self.workdir, f"replay_{self.scale}")
        if not os.path.exists(os.path.join(path, "_delta_log")):
            synth_delta_log(path, cfg["commits"], cfg["files_per_commit"])
        table = Table.for_path(path, TpuEngine())
        snap = table.latest_snapshot()
        _ = snap.num_files
        with self.timed("checkpoint-write", extra={"numFiles": snap.num_files}):
            info = write_checkpoint(table.engine, snap)
        size = info.sizeInBytes or 0
        dur_s = self.report.results[-1].duration_ms / 1000
        if size:
            self.metric("checkpoint_write_mb_per_sec", size / 1e6 / dur_s, "MB/s")
        self.metric("checkpoint_files_per_sec", snap.num_files / dur_s, "files/s")
        # re-load from checkpoint
        with self.timed("reload-from-checkpoint"):
            snap2 = Table.for_path(path, TpuEngine()).latest_snapshot()
            _ = snap2.num_files
        return self.report


class OptimizeBenchmark(Benchmark):
    name = "optimize"

    def run(self):
        import delta_tpu.api as dta
        from delta_tpu.table import Table

        cfg = SCALES[self.scale]
        rows = cfg["rows"]
        path = os.path.join(self.workdir, f"optimize_{self.scale}")
        shutil.rmtree(path, ignore_errors=True)
        rng = np.random.default_rng(1)
        n_commits = 20
        per = rows // n_commits
        for i in range(n_commits):
            data = pa.table({
                "k1": pa.array(rng.integers(0, 1 << 30, per).astype(np.int64)),
                "k2": pa.array(rng.integers(0, 1 << 30, per).astype(np.int64)),
                "k3": pa.array(rng.integers(0, 1 << 30, per).astype(np.int64)),
                "payload": pa.array(rng.normal(size=per)),
            })
            dta.write_table(path, data)
        table = Table.for_path(path)
        with self.timed("compaction", extra={"rows": rows}):
            m = table.optimize().execute_compaction()
        self.metric("compaction_files_per_sec",
                    m.num_files_removed / (self.report.results[-1].duration_ms / 1000),
                    "files/s")
        with self.timed("zorder-3col", extra={"rows": rows}):
            mz = Table.for_path(path).optimize().execute_zorder_by("k1", "k2", "k3")
        dur_s = self.report.results[-1].duration_ms / 1000
        self.metric("zorder_rows_per_sec", rows / dur_s, "rows/s")
        # curve-key kernel alone
        from delta_tpu.ops.zorder import zorder_sort_indices

        cols = [rng.integers(0, 1 << 30, rows).astype(np.int64) for _ in range(3)]
        zorder_sort_indices([c[:1000] for c in cols])  # compile
        with self.timed("curve-key-kernel", extra={"rows": rows}):
            zorder_sort_indices(cols)
        dur_s = self.report.results[-1].duration_ms / 1000
        self.metric("curve_key_rows_per_sec", rows / dur_s, "rows/s")
        return self.report


class MergeBenchmark(Benchmark):
    name = "merge"

    def run(self):
        import delta_tpu.api as dta
        from delta_tpu.commands.merge import merge
        from delta_tpu.expressions import col
        from delta_tpu.table import Table

        cfg = SCALES[self.scale]
        rows = cfg["rows"]
        path = os.path.join(self.workdir, f"merge_{self.scale}")
        shutil.rmtree(path, ignore_errors=True)
        rng = np.random.default_rng(2)
        base = pa.table({
            "id": pa.array(np.arange(rows, dtype=np.int64)),
            "v": pa.array(rng.normal(size=rows)),
        })
        dta.write_table(path, base, target_rows_per_file=max(1, rows // 20))
        n_src = rows // 10
        src = pa.table({
            "id": pa.array(np.concatenate([
                rng.choice(rows, n_src // 2, replace=False),
                np.arange(rows, rows + n_src // 2),
            ]).astype(np.int64)),
            "v": pa.array(rng.normal(size=2 * (n_src // 2))),
        })
        with self.timed("merge-upsert", extra={"source_rows": src.num_rows}):
            m = (merge(Table.for_path(path), src,
                       on=col("target.id") == col("source.id"))
                 .when_matched_update(set={"v": col("source.v")})
                 .when_not_matched_insert_all()
                 .execute())
        dur_s = self.report.results[-1].duration_ms / 1000
        self.metric("merge_source_rows_per_sec", src.num_rows / dur_s, "rows/s",
                    updated=m.num_target_rows_updated,
                    inserted=m.num_target_rows_inserted)
        return self.report


class StreamingBenchmark(Benchmark):
    name = "streaming"

    def run(self):
        from delta_tpu.streaming import DeltaSink

        cfg = SCALES[self.scale]
        rows = cfg["rows"]
        path = os.path.join(self.workdir, f"streaming_{self.scale}")
        shutil.rmtree(path, ignore_errors=True)
        rng = np.random.default_rng(3)
        sink = DeltaSink(path, query_id="bench")
        n_batches = 20
        per = max(1, rows // n_batches)
        with self.timed("ingest", extra={"batches": n_batches, "rows": rows}):
            for b in range(n_batches):
                data = pa.table({
                    "id": pa.array(np.arange(b * per, (b + 1) * per, dtype=np.int64)),
                    "v": pa.array(rng.normal(size=per)),
                })
                sink.add_batch(b, data)
        dur_s = self.report.results[-1].duration_ms / 1000
        self.metric("ingest_batches_per_sec", n_batches / dur_s, "batches/s")
        self.metric("ingest_rows_per_sec", n_batches * per / dur_s, "rows/s")
        return self.report


class TpcdsLiteBenchmark(Benchmark):
    """Star-schema load + query shapes, the role of the reference's
    TPC-DS harness (`benchmarks/src/main/scala/benchmark/
    TPCDSDataLoad.scala:71`, `TPCDSBenchmark.scala:74`). A dsdgen-scale
    run needs a Spark cluster; this generates a store_sales-shaped fact
    table (partitioned by month) plus item/date dims, loads them as
    Delta tables, and times representative query shapes through the
    framework surface: partition-pruned scans, stats-skipped range
    scans, dimension joins + aggregation (Arrow host compute — the
    framework's query-integration layer), and full-scan aggregates."""

    name = "tpcds_lite"

    FACT_ROWS = {"smoke": 50_000, "small": 1_000_000,
                 "medium": 10_000_000, "large": 25_000_000,
                 "full": 50_000_000}

    def run(self):
        import delta_tpu.api as dta
        from delta_tpu.expressions import col, lit

        rows = self.FACT_ROWS[self.scale]
        root = os.path.join(self.workdir, f"tpcds_{self.scale}")
        shutil.rmtree(root, ignore_errors=True)
        rng = np.random.default_rng(42)

        n_items = max(100, rows // 1000)
        item = pa.table({
            "i_item_sk": pa.array(np.arange(n_items, dtype=np.int64)),
            "i_brand_id": pa.array(rng.integers(0, 50, n_items)),
            "i_category_id": pa.array(rng.integers(0, 10, n_items)),
        })
        date_dim = pa.table({
            "d_date_sk": pa.array(np.arange(365 * 5, dtype=np.int64)),
            "d_year": pa.array(2019 + np.arange(365 * 5) // 365),
            "d_moy": pa.array((np.arange(365 * 5) % 365) // 31 + 1),
        })
        with self.timed("load_dims"):
            dta.write_table(os.path.join(root, "item"), item)
            dta.write_table(os.path.join(root, "date_dim"), date_dim)

        fact_path = os.path.join(root, "store_sales")
        # at least 12 chunks so every month partition exists at any scale
        chunk = min(max(1, rows // 12), 1_000_000)
        with self.timed("load_fact", extra={"rows": rows}):
            for start in range(0, rows, chunk):
                n = min(chunk, rows - start)
                ci = start // chunk
                month = ci % 12 + 1
                # each chunk covers a narrow date window (like real
                # time-ordered ingest) so per-file min/max stats are
                # tight and range queries can actually skip files
                date_base = (ci * 150) % (365 * 5 - 150)
                data = pa.table({
                    "ss_sold_date_sk": pa.array(
                        (date_base
                         + rng.integers(0, 150, n)).astype(np.int64)),
                    "ss_item_sk": pa.array(
                        rng.integers(0, n_items, n).astype(np.int64)),
                    "ss_quantity": pa.array(rng.integers(1, 100, n)),
                    "ss_sales_price": pa.array(rng.uniform(1, 500, n)),
                    "ss_month": pa.array(np.full(n, f"{month:02d}")),
                })
                dta.write_table(fact_path, data, mode="append",
                                partition_by=["ss_month"])
        dur_s = self.report.results[-1].duration_ms / 1000
        self.metric("load_rows_per_sec", rows / dur_s, "rows/s")

        import pyarrow.compute as pc

        from delta_tpu.table import Table

        snap = Table.for_path(fact_path).latest_snapshot()
        n_files = len(snap.state.add_files_table)

        # Q1: partition-pruned aggregate (one month of sales)
        with self.timed("q1_partition_prune"):
            scan1 = snap.scan(filter=col("ss_month") == lit("03"))
            t = scan1.to_arrow()
            q1 = pc.sum(t.column("ss_sales_price")).as_py() or 0.0
        self.metric("q1_files_scanned", len(scan1.files()), "files",
                    total=n_files)

        # Q2: stats-skipped range scan (narrow date window; chunks are
        # date-correlated so per-file stats prune)
        with self.timed("q2_range_skip"):
            pred = (col("ss_sold_date_sk") >= lit(100)) & (
                col("ss_sold_date_sk") < lit(130))
            scan2 = snap.scan(filter=pred)
            t = scan2.to_arrow()
            q2 = t.num_rows
        self.metric("q2_files_scanned", len(scan2.files()), "files",
                    total=n_files)

        # Q3: fact-dim join + group-by through the SQL frontend
        # (TPC-DS Q3 shape: brand revenue for one year)
        from delta_tpu.sql import sql as run_sql

        with self.timed("q3_join_groupby_sql"):
            out = run_sql(
                f"SELECT i.i_brand_id AS brand, "
                f"SUM(f.ss_sales_price) AS rev "
                f"FROM '{fact_path}' f "
                f"JOIN '{os.path.join(root, 'date_dim')}' d "
                f"ON f.ss_sold_date_sk = d.d_date_sk "
                f"JOIN '{os.path.join(root, 'item')}' i "
                f"ON f.ss_item_sk = i.i_item_sk "
                f"WHERE d.d_year = 2020 "
                f"GROUP BY i.i_brand_id ORDER BY rev DESC LIMIT 10")
            q3 = out.num_rows

        # Q4: full-scan aggregate
        with self.timed("q4_full_agg"):
            t = snap.scan(columns=["ss_quantity"]).to_arrow()
            q4 = pc.sum(t.column("ss_quantity")).as_py()

        self.metric("fact_rows", rows, "rows", q1=round(q1, 2), q2=q2,
                    q3=q3, q4=int(q4))
        return self.report


class TpcdsBenchmark(Benchmark):
    """The real TPC-DS harness: loads the 19-table TPC-DS schema as
    Delta tables (`benchmarks/tpcds_data.py`, the dsdgen role of the
    reference's `TPCDSDataLoad.scala:71`) and times every VERBATIM
    query in `benchmarks/tpcds_queries.py` through the sqlengine
    (`TPCDSBenchmark.scala:74` role) on BOTH substrates — the
    TpuEngine device spine (`ops/sqlops.py` kernels) and the
    HostEngine pandas path — plus the independent sqlite oracle as the
    external comparison column. Two timed iterations per engine query
    (cold + warm); correctness is asserted separately in
    tests/test_tpcds.py."""

    name = "tpcds"

    # store_sales rows; dims scale proportionally. "large" ≈ 1.4GB of
    # Delta-backed Parquet across the 19 tables.
    FACT_ROWS = {"smoke": 20_000, "small": 200_000,
                 "medium": 2_000_000, "large": 10_000_000,
                 "full": 25_000_000}

    def run(self):
        from benchmarks.tpcds_data import generate, load_delta
        from benchmarks.tpcds_queries import QUERIES
        from delta_tpu.catalog import Catalog
        from delta_tpu.engine.host import HostEngine
        from delta_tpu.sqlengine import execute_select

        rows = self.FACT_ROWS[self.scale]
        root = os.path.join(self.workdir, f"tpcds_full_{self.scale}")
        shutil.rmtree(root, ignore_errors=True)
        with self.timed("load", rows=rows):
            catalog = load_delta(root, scale=rows)
        host_catalog = Catalog(root, engine=HostEngine())
        size = sum(
            os.path.getsize(os.path.join(dp, f))
            for dp, _, fs in os.walk(root) for f in fs)
        self.metric("dataset_bytes", size, "bytes", fact_rows=rows)

        oracle = None
        if os.environ.get("TPCDS_BENCH_ORACLE", "1") != "0":
            from tests.tpcds_sqlite_oracle import SqliteOracle

            t0 = time.perf_counter()
            oracle = SqliteOracle(generate(rows))
            n_idx = oracle.create_indexes()
            self.metric("oracle_load_ms",
                        (time.perf_counter() - t0) * 1000, "ms",
                        indexes=n_idx)

        # TPCDS_BENCH_SUBSTRATES=host|device|device,host (default both;
        # the tunnel deployment's medium runs use host — the small
        # report carries the device column, and each device query there
        # already costs seconds-to-minutes over the link)
        wanted = [s.strip() for s in os.environ.get(
            "TPCDS_BENCH_SUBSTRATES", "device,host").split(",")
            if s.strip()]
        unknown = set(wanted) - {"device", "host"}
        if unknown or not wanted:
            raise ValueError(
                f"TPCDS_BENCH_SUBSTRATES must name device and/or "
                f"host; got {wanted!r}")
        pairs = [p for p in (("device", catalog), ("host", host_catalog))
                 if p[0] in wanted]
        totals = {s: 0.0 for s, _c in pairs}
        oracle_total, oracle_done, oracle_skipped = 0.0, 0, 0
        saved_flag = os.environ.get("DELTA_TPU_DEVICE_SQL")
        try:
            for name, q in QUERIES.items():
                for substrate, cat in pairs:
                    # pin the substrate: the device column must measure the
                    # device spine even where the link auto-gate would
                    # decline it (that cost is exactly what it reports)
                    os.environ["DELTA_TPU_DEVICE_SQL"] = (
                        "1" if substrate == "device" else "0")
                    for it in range(2):
                        t0 = time.perf_counter()
                        out = execute_select(q, catalog=cat)
                        dt = (time.perf_counter() - t0) * 1000
                        self.report.results.append(QueryResult(
                            name, it, dt, {"rows": out.num_rows,
                                           "substrate": substrate}))
                        print(f"  {name}[{substrate}:{it}]: {dt:,.1f} ms "
                              f"({out.num_rows} rows)", file=sys.stderr)
                        if it == 1:
                            totals[substrate] += dt
                if oracle is not None:
                    t0 = time.perf_counter()
                    try:
                        res = oracle.run_with_timeout(q, seconds=60.0)
                        dt = (time.perf_counter() - t0) * 1000
                        if res is None:
                            oracle_skipped += 1
                            self.report.results.append(QueryResult(
                                name, 0, dt, {"substrate": "oracle",
                                              "error": "timeout"}))
                            print(f"  {name}[oracle]: TIMEOUT",
                                  file=sys.stderr)
                            continue
                        orows = len(res)
                        self.report.results.append(QueryResult(
                            name, 0, dt, {"rows": orows,
                                          "substrate": "oracle"}))
                        oracle_total += dt
                        oracle_done += 1
                        print(f"  {name}[oracle]: {dt:,.1f} ms",
                              file=sys.stderr)
                    except Exception as exc:  # q67 rollup depth
                        oracle_skipped += 1
                        self.report.results.append(QueryResult(
                            name, 0, float("nan"),
                            {"substrate": "oracle",
                             "error": str(exc)[:120]}))
        finally:
            # never leak the substrate pin (a mid-loop
            # failure would force it process-wide)
            if saved_flag is None:
                os.environ.pop("DELTA_TPU_DEVICE_SQL", None)
            else:
                os.environ["DELTA_TPU_DEVICE_SQL"] = saved_flag
        for substrate, total in totals.items():
            self.metric(f"tpcds_warm_total_{substrate}", total, "ms",
                        queries=len(QUERIES))
        if oracle is not None:
            # cold single-run timings over the queries sqlite can run —
            # NOT comparable 1:1 with the warm engine totals; per-query
            # rows carry the honest comparison
            self.metric("tpcds_oracle_total_cold", oracle_total, "ms",
                        queries=oracle_done, skipped=oracle_skipped)
        self.metric("tpcds_warm_total",
                    totals.get("device", totals.get("host", 0.0)),
                    "ms", queries=len(QUERIES))
        return self.report


BENCHMARKS = {
    b.name: b
    for b in (ReplayBenchmark, CheckpointBenchmark, OptimizeBenchmark,
              MergeBenchmark, StreamingBenchmark, TpcdsLiteBenchmark,
              TpcdsBenchmark)
}

"""Verbatim TPC-DS query texts (subset runnable by the sqlengine).

These are the standard TPC-DS benchmark queries as shipped in the
reference harness (`benchmarks/src/main/scala/benchmark/
TPCDSBenchmarkQueries.scala`, itself generated from the public TPC-DS
v2.4 templates). Texts are UNMODIFIED - the point is that the SQL
engine runs them as-is (VERDICT r2 next-steps #3).
"""

QUERIES = {
    "q3": r"""
select  dt.d_year
       ,item.i_brand_id brand_id
       ,item.i_brand brand
       ,sum(ss_sales_price) sum_agg
 from  date_dim dt
      ,store_sales
      ,item
 where dt.d_date_sk = store_sales.ss_sold_date_sk
   and store_sales.ss_item_sk = item.i_item_sk
   and item.i_manufact_id = 816
   and dt.d_moy=11
 group by dt.d_year
      ,item.i_brand
      ,item.i_brand_id
 order by dt.d_year
         ,sum_agg desc
         ,brand_id
 limit 100
""",
    "q7": r"""
select  i_item_id,
        avg(ss_quantity) agg1,
        avg(ss_list_price) agg2,
        avg(ss_coupon_amt) agg3,
        avg(ss_sales_price) agg4
 from store_sales, customer_demographics, date_dim, item, promotion
 where ss_sold_date_sk = d_date_sk and
       ss_item_sk = i_item_sk and
       ss_cdemo_sk = cd_demo_sk and
       ss_promo_sk = p_promo_sk and
       cd_gender = 'F' and
       cd_marital_status = 'W' and
       cd_education_status = 'College' and
       (p_channel_email = 'N' or p_channel_event = 'N') and
       d_year = 2001
 group by i_item_id
 order by i_item_id
 limit 100
""",
    "q19": r"""
select  i_brand_id brand_id, i_brand brand, i_manufact_id, i_manufact,
 	sum(ss_ext_sales_price) ext_price
 from date_dim, store_sales, item,customer,customer_address,store
 where d_date_sk = ss_sold_date_sk
   and ss_item_sk = i_item_sk
   and i_manager_id=26
   and d_moy=12
   and d_year=2000
   and ss_customer_sk = c_customer_sk
   and c_current_addr_sk = ca_address_sk
   and substr(ca_zip,1,5) <> substr(s_zip,1,5)
   and ss_store_sk = s_store_sk
 group by i_brand
      ,i_brand_id
      ,i_manufact_id
      ,i_manufact
 order by ext_price desc
         ,i_brand
         ,i_brand_id
         ,i_manufact_id
         ,i_manufact
limit 100 
""",
    "q26": r"""
select  i_item_id,
        avg(cs_quantity) agg1,
        avg(cs_list_price) agg2,
        avg(cs_coupon_amt) agg3,
        avg(cs_sales_price) agg4
 from catalog_sales, customer_demographics, date_dim, item, promotion
 where cs_sold_date_sk = d_date_sk and
       cs_item_sk = i_item_sk and
       cs_bill_cdemo_sk = cd_demo_sk and
       cs_promo_sk = p_promo_sk and
       cd_gender = 'F' and
       cd_marital_status = 'M' and
       cd_education_status = '2 yr Degree' and
       (p_channel_email = 'N' or p_channel_event = 'N') and
       d_year = 2002
 group by i_item_id
 order by i_item_id
 limit 100
""",
    "q37": r"""
select  i_item_id
       ,i_item_desc
       ,i_current_price
 from item, inventory, date_dim, catalog_sales
 where i_current_price between 35 and 35 + 30
 and inv_item_sk = i_item_sk
 and d_date_sk=inv_date_sk
 and d_date between cast('2001-01-20' as date) and (cast('2001-01-20' as date) + interval 60 days)
 and i_manufact_id in (928,715,942,861)
 and inv_quantity_on_hand between 100 and 500
 and cs_item_sk = i_item_sk
 group by i_item_id,i_item_desc,i_current_price
 order by i_item_id
 limit 100
""",
    "q42": r"""
select  dt.d_year
 	,item.i_category_id
 	,item.i_category
 	,sum(ss_ext_sales_price)
 from 	date_dim dt
 	,store_sales
 	,item
 where dt.d_date_sk = store_sales.ss_sold_date_sk
 	and store_sales.ss_item_sk = item.i_item_sk
 	and item.i_manager_id = 1
 	and dt.d_moy=11
 	and dt.d_year=2002
 group by 	dt.d_year
 		,item.i_category_id
 		,item.i_category
 order by       sum(ss_ext_sales_price) desc,dt.d_year
 		,item.i_category_id
 		,item.i_category
limit 100 
""",
    "q43": r"""
select  s_store_name, s_store_id,
        sum(case when (d_day_name='Sunday') then ss_sales_price else null end) sun_sales,
        sum(case when (d_day_name='Monday') then ss_sales_price else null end) mon_sales,
        sum(case when (d_day_name='Tuesday') then ss_sales_price else  null end) tue_sales,
        sum(case when (d_day_name='Wednesday') then ss_sales_price else null end) wed_sales,
        sum(case when (d_day_name='Thursday') then ss_sales_price else null end) thu_sales,
        sum(case when (d_day_name='Friday') then ss_sales_price else null end) fri_sales,
        sum(case when (d_day_name='Saturday') then ss_sales_price else null end) sat_sales
 from date_dim, store_sales, store
 where d_date_sk = ss_sold_date_sk and
       s_store_sk = ss_store_sk and
       s_gmt_offset = -6 and
       d_year = 1999
 group by s_store_name, s_store_id
 order by s_store_name, s_store_id,sun_sales,mon_sales,tue_sales,wed_sales,thu_sales,fri_sales,sat_sales
 limit 100
""",
    "q52": r"""
select  dt.d_year
 	,item.i_brand_id brand_id
 	,item.i_brand brand
 	,sum(ss_ext_sales_price) ext_price
 from date_dim dt
     ,store_sales
     ,item
 where dt.d_date_sk = store_sales.ss_sold_date_sk
    and store_sales.ss_item_sk = item.i_item_sk
    and item.i_manager_id = 1
    and dt.d_moy=11
    and dt.d_year=2001
 group by dt.d_year
 	,item.i_brand
 	,item.i_brand_id
 order by dt.d_year
 	,ext_price desc
 	,brand_id
limit 100 
""",
    "q55": r"""
select  i_brand_id brand_id, i_brand brand,
 	sum(ss_ext_sales_price) ext_price
 from date_dim, store_sales, item
 where d_date_sk = ss_sold_date_sk
 	and ss_item_sk = i_item_sk
 	and i_manager_id=87
 	and d_moy=11
 	and d_year=2001
 group by i_brand, i_brand_id
 order by ext_price desc, i_brand_id
limit 100 
""",
    "q62": r"""
select
   substr(w_warehouse_name,1,20)
  ,sm_type
  ,web_name
  ,sum(case when (ws_ship_date_sk - ws_sold_date_sk <= 30 ) then 1 else 0 end)  as `30 days`
  ,sum(case when (ws_ship_date_sk - ws_sold_date_sk > 30) and
                 (ws_ship_date_sk - ws_sold_date_sk <= 60) then 1 else 0 end )  as `31-60 days`
  ,sum(case when (ws_ship_date_sk - ws_sold_date_sk > 60) and
                 (ws_ship_date_sk - ws_sold_date_sk <= 90) then 1 else 0 end)  as `61-90 days`
  ,sum(case when (ws_ship_date_sk - ws_sold_date_sk > 90) and
                 (ws_ship_date_sk - ws_sold_date_sk <= 120) then 1 else 0 end)  as `91-120 days`
  ,sum(case when (ws_ship_date_sk - ws_sold_date_sk  > 120) then 1 else 0 end)  as `>120 days`
from
   web_sales
  ,warehouse
  ,ship_mode
  ,web_site
  ,date_dim
where
    d_month_seq between 1194 and 1194 + 11
and ws_ship_date_sk   = d_date_sk
and ws_warehouse_sk   = w_warehouse_sk
and ws_ship_mode_sk   = sm_ship_mode_sk
and ws_web_site_sk    = web_site_sk
group by
   substr(w_warehouse_name,1,20)
  ,sm_type
  ,web_name
order by substr(w_warehouse_name,1,20)
        ,sm_type
       ,web_name
limit 100
""",
    "q82": r"""
select  i_item_id
       ,i_item_desc
       ,i_current_price
 from item, inventory, date_dim, store_sales
 where i_current_price between 82 and 82+30
 and inv_item_sk = i_item_sk
 and d_date_sk=inv_date_sk
 and d_date between cast('2002-03-10' as date) and (cast('2002-03-10' as date) +  INTERVAL 60 days)
 and i_manufact_id in (941,920,105,693)
 and inv_quantity_on_hand between 100 and 500
 and ss_item_sk = i_item_sk
 group by i_item_id,i_item_desc,i_current_price
 order by i_item_id
 limit 100
""",
    "q96": r"""
select  count(*)
from store_sales
    ,household_demographics
    ,time_dim, store
where ss_sold_time_sk = time_dim.t_time_sk
    and ss_hdemo_sk = household_demographics.hd_demo_sk
    and ss_store_sk = s_store_sk
    and time_dim.t_hour = 16
    and time_dim.t_minute >= 30
    and household_demographics.hd_dep_count = 4
    and store.s_store_name = 'ese'
order by count(*)
limit 100
""",
}

"""Verbatim TPC-DS query texts (subset runnable by the sqlengine).

These are the standard TPC-DS benchmark queries as shipped in the
reference harness (`benchmarks/src/main/scala/benchmark/
TPCDSBenchmarkQueries.scala`, itself generated from the public TPC-DS
v2.4 templates). Texts are UNMODIFIED - the point is that the SQL
engine runs them as-is (VERDICT r2 next-steps #3).
"""

QUERIES = {
    "q3": r"""
select  dt.d_year
       ,item.i_brand_id brand_id
       ,item.i_brand brand
       ,sum(ss_sales_price) sum_agg
 from  date_dim dt
      ,store_sales
      ,item
 where dt.d_date_sk = store_sales.ss_sold_date_sk
   and store_sales.ss_item_sk = item.i_item_sk
   and item.i_manufact_id = 816
   and dt.d_moy=11
 group by dt.d_year
      ,item.i_brand
      ,item.i_brand_id
 order by dt.d_year
         ,sum_agg desc
         ,brand_id
 limit 100
""",
    "q7": r"""
select  i_item_id,
        avg(ss_quantity) agg1,
        avg(ss_list_price) agg2,
        avg(ss_coupon_amt) agg3,
        avg(ss_sales_price) agg4
 from store_sales, customer_demographics, date_dim, item, promotion
 where ss_sold_date_sk = d_date_sk and
       ss_item_sk = i_item_sk and
       ss_cdemo_sk = cd_demo_sk and
       ss_promo_sk = p_promo_sk and
       cd_gender = 'F' and
       cd_marital_status = 'W' and
       cd_education_status = 'College' and
       (p_channel_email = 'N' or p_channel_event = 'N') and
       d_year = 2001
 group by i_item_id
 order by i_item_id
 limit 100
""",
    "q19": r"""
select  i_brand_id brand_id, i_brand brand, i_manufact_id, i_manufact,
 	sum(ss_ext_sales_price) ext_price
 from date_dim, store_sales, item,customer,customer_address,store
 where d_date_sk = ss_sold_date_sk
   and ss_item_sk = i_item_sk
   and i_manager_id=26
   and d_moy=12
   and d_year=2000
   and ss_customer_sk = c_customer_sk
   and c_current_addr_sk = ca_address_sk
   and substr(ca_zip,1,5) <> substr(s_zip,1,5)
   and ss_store_sk = s_store_sk
 group by i_brand
      ,i_brand_id
      ,i_manufact_id
      ,i_manufact
 order by ext_price desc
         ,i_brand
         ,i_brand_id
         ,i_manufact_id
         ,i_manufact
limit 100 
""",
    "q26": r"""
select  i_item_id,
        avg(cs_quantity) agg1,
        avg(cs_list_price) agg2,
        avg(cs_coupon_amt) agg3,
        avg(cs_sales_price) agg4
 from catalog_sales, customer_demographics, date_dim, item, promotion
 where cs_sold_date_sk = d_date_sk and
       cs_item_sk = i_item_sk and
       cs_bill_cdemo_sk = cd_demo_sk and
       cs_promo_sk = p_promo_sk and
       cd_gender = 'F' and
       cd_marital_status = 'M' and
       cd_education_status = '2 yr Degree' and
       (p_channel_email = 'N' or p_channel_event = 'N') and
       d_year = 2002
 group by i_item_id
 order by i_item_id
 limit 100
""",
    "q37": r"""
select  i_item_id
       ,i_item_desc
       ,i_current_price
 from item, inventory, date_dim, catalog_sales
 where i_current_price between 35 and 35 + 30
 and inv_item_sk = i_item_sk
 and d_date_sk=inv_date_sk
 and d_date between cast('2001-01-20' as date) and (cast('2001-01-20' as date) + interval 60 days)
 and i_manufact_id in (928,715,942,861)
 and inv_quantity_on_hand between 100 and 500
 and cs_item_sk = i_item_sk
 group by i_item_id,i_item_desc,i_current_price
 order by i_item_id
 limit 100
""",
    "q42": r"""
select  dt.d_year
 	,item.i_category_id
 	,item.i_category
 	,sum(ss_ext_sales_price)
 from 	date_dim dt
 	,store_sales
 	,item
 where dt.d_date_sk = store_sales.ss_sold_date_sk
 	and store_sales.ss_item_sk = item.i_item_sk
 	and item.i_manager_id = 1
 	and dt.d_moy=11
 	and dt.d_year=2002
 group by 	dt.d_year
 		,item.i_category_id
 		,item.i_category
 order by       sum(ss_ext_sales_price) desc,dt.d_year
 		,item.i_category_id
 		,item.i_category
limit 100 
""",
    "q43": r"""
select  s_store_name, s_store_id,
        sum(case when (d_day_name='Sunday') then ss_sales_price else null end) sun_sales,
        sum(case when (d_day_name='Monday') then ss_sales_price else null end) mon_sales,
        sum(case when (d_day_name='Tuesday') then ss_sales_price else  null end) tue_sales,
        sum(case when (d_day_name='Wednesday') then ss_sales_price else null end) wed_sales,
        sum(case when (d_day_name='Thursday') then ss_sales_price else null end) thu_sales,
        sum(case when (d_day_name='Friday') then ss_sales_price else null end) fri_sales,
        sum(case when (d_day_name='Saturday') then ss_sales_price else null end) sat_sales
 from date_dim, store_sales, store
 where d_date_sk = ss_sold_date_sk and
       s_store_sk = ss_store_sk and
       s_gmt_offset = -6 and
       d_year = 1999
 group by s_store_name, s_store_id
 order by s_store_name, s_store_id,sun_sales,mon_sales,tue_sales,wed_sales,thu_sales,fri_sales,sat_sales
 limit 100
""",
    "q52": r"""
select  dt.d_year
 	,item.i_brand_id brand_id
 	,item.i_brand brand
 	,sum(ss_ext_sales_price) ext_price
 from date_dim dt
     ,store_sales
     ,item
 where dt.d_date_sk = store_sales.ss_sold_date_sk
    and store_sales.ss_item_sk = item.i_item_sk
    and item.i_manager_id = 1
    and dt.d_moy=11
    and dt.d_year=2001
 group by dt.d_year
 	,item.i_brand
 	,item.i_brand_id
 order by dt.d_year
 	,ext_price desc
 	,brand_id
limit 100 
""",
    "q55": r"""
select  i_brand_id brand_id, i_brand brand,
 	sum(ss_ext_sales_price) ext_price
 from date_dim, store_sales, item
 where d_date_sk = ss_sold_date_sk
 	and ss_item_sk = i_item_sk
 	and i_manager_id=87
 	and d_moy=11
 	and d_year=2001
 group by i_brand, i_brand_id
 order by ext_price desc, i_brand_id
limit 100 
""",
    "q62": r"""
select
   substr(w_warehouse_name,1,20)
  ,sm_type
  ,web_name
  ,sum(case when (ws_ship_date_sk - ws_sold_date_sk <= 30 ) then 1 else 0 end)  as `30 days`
  ,sum(case when (ws_ship_date_sk - ws_sold_date_sk > 30) and
                 (ws_ship_date_sk - ws_sold_date_sk <= 60) then 1 else 0 end )  as `31-60 days`
  ,sum(case when (ws_ship_date_sk - ws_sold_date_sk > 60) and
                 (ws_ship_date_sk - ws_sold_date_sk <= 90) then 1 else 0 end)  as `61-90 days`
  ,sum(case when (ws_ship_date_sk - ws_sold_date_sk > 90) and
                 (ws_ship_date_sk - ws_sold_date_sk <= 120) then 1 else 0 end)  as `91-120 days`
  ,sum(case when (ws_ship_date_sk - ws_sold_date_sk  > 120) then 1 else 0 end)  as `>120 days`
from
   web_sales
  ,warehouse
  ,ship_mode
  ,web_site
  ,date_dim
where
    d_month_seq between 1194 and 1194 + 11
and ws_ship_date_sk   = d_date_sk
and ws_warehouse_sk   = w_warehouse_sk
and ws_ship_mode_sk   = sm_ship_mode_sk
and ws_web_site_sk    = web_site_sk
group by
   substr(w_warehouse_name,1,20)
  ,sm_type
  ,web_name
order by substr(w_warehouse_name,1,20)
        ,sm_type
       ,web_name
limit 100
""",
    "q82": r"""
select  i_item_id
       ,i_item_desc
       ,i_current_price
 from item, inventory, date_dim, store_sales
 where i_current_price between 82 and 82+30
 and inv_item_sk = i_item_sk
 and d_date_sk=inv_date_sk
 and d_date between cast('2002-03-10' as date) and (cast('2002-03-10' as date) +  INTERVAL 60 days)
 and i_manufact_id in (941,920,105,693)
 and inv_quantity_on_hand between 100 and 500
 and ss_item_sk = i_item_sk
 group by i_item_id,i_item_desc,i_current_price
 order by i_item_id
 limit 100
""",
    "q96": r"""
select  count(*)
from store_sales
    ,household_demographics
    ,time_dim, store
where ss_sold_time_sk = time_dim.t_time_sk
    and ss_hdemo_sk = household_demographics.hd_demo_sk
    and ss_store_sk = s_store_sk
    and time_dim.t_hour = 16
    and time_dim.t_minute >= 30
    and household_demographics.hd_dep_count = 4
    and store.s_store_name = 'ese'
order by count(*)
limit 100
""",
}

# --- added in round 4: window-function + subquery shapes (verbatim) ---

QUERIES["q12"] = r"""
select  i_item_id
      ,i_item_desc
      ,i_category
      ,i_class
      ,i_current_price
      ,sum(ws_ext_sales_price) as itemrevenue
      ,sum(ws_ext_sales_price)*100/sum(sum(ws_ext_sales_price)) over
          (partition by i_class) as revenueratio
from
	web_sales
    	,item
    	,date_dim
where
	ws_item_sk = i_item_sk
  	and i_category in ('Men', 'Books', 'Children')
  	and ws_sold_date_sk = d_date_sk
	and d_date between cast('1998-03-28' as date)
				and (cast('1998-03-28' as date) + INTERVAL 30 days)
group by
	i_item_id
        ,i_item_desc
        ,i_category
        ,i_class
        ,i_current_price
order by
	i_category
        ,i_class
        ,i_item_id
        ,i_item_desc
        ,revenueratio
limit 100
"""

QUERIES["q15"] = r"""
select  ca_zip
       ,sum(cs_sales_price)
 from catalog_sales
     ,customer
     ,customer_address
     ,date_dim
 where cs_bill_customer_sk = c_customer_sk
 	and c_current_addr_sk = ca_address_sk
 	and ( substr(ca_zip,1,5) in ('85669', '86197','88274','83405','86475',
                                   '85392', '85460', '80348', '81792')
 	      or ca_state in ('CA','WA','GA')
 	      or cs_sales_price > 500)
 	and cs_sold_date_sk = d_date_sk
 	and d_qoy = 1 and d_year = 2000
 group by ca_zip
 order by ca_zip
 limit 100
"""

QUERIES["q17"] = r"""
select  i_item_id
       ,i_item_desc
       ,s_state
       ,count(ss_quantity) as store_sales_quantitycount
       ,avg(ss_quantity) as store_sales_quantityave
       ,stddev_samp(ss_quantity) as store_sales_quantitystdev
       ,stddev_samp(ss_quantity)/avg(ss_quantity) as store_sales_quantitycov
       ,count(sr_return_quantity) as store_returns_quantitycount
       ,avg(sr_return_quantity) as store_returns_quantityave
       ,stddev_samp(sr_return_quantity) as store_returns_quantitystdev
       ,stddev_samp(sr_return_quantity)/avg(sr_return_quantity) as store_returns_quantitycov
       ,count(cs_quantity) as catalog_sales_quantitycount ,avg(cs_quantity) as catalog_sales_quantityave
       ,stddev_samp(cs_quantity) as catalog_sales_quantitystdev
       ,stddev_samp(cs_quantity)/avg(cs_quantity) as catalog_sales_quantitycov
 from store_sales
     ,store_returns
     ,catalog_sales
     ,date_dim d1
     ,date_dim d2
     ,date_dim d3
     ,store
     ,item
 where d1.d_quarter_name = '1999Q1'
   and d1.d_date_sk = ss_sold_date_sk
   and i_item_sk = ss_item_sk
   and s_store_sk = ss_store_sk
   and ss_customer_sk = sr_customer_sk
   and ss_item_sk = sr_item_sk
   and ss_ticket_number = sr_ticket_number
   and sr_returned_date_sk = d2.d_date_sk
   and d2.d_quarter_name in ('1999Q1','1999Q2','1999Q3')
   and sr_customer_sk = cs_bill_customer_sk
   and sr_item_sk = cs_item_sk
   and cs_sold_date_sk = d3.d_date_sk
   and d3.d_quarter_name in ('1999Q1','1999Q2','1999Q3')
 group by i_item_id
         ,i_item_desc
         ,s_state
 order by i_item_id
         ,i_item_desc
         ,s_state
limit 100
"""

QUERIES["q20"] = r"""
select  i_item_id
       ,i_item_desc
       ,i_category
       ,i_class
       ,i_current_price
       ,sum(cs_ext_sales_price) as itemrevenue
       ,sum(cs_ext_sales_price)*100/sum(sum(cs_ext_sales_price)) over
           (partition by i_class) as revenueratio
 from	catalog_sales
     ,item
     ,date_dim
 where cs_item_sk = i_item_sk
   and i_category in ('Books', 'Home', 'Jewelry')
   and cs_sold_date_sk = d_date_sk
 and d_date between cast('1998-05-08' as date)
 				and (cast('1998-05-08' as date) + INTERVAL 30 days)
 group by i_item_id
         ,i_item_desc
         ,i_category
         ,i_class
         ,i_current_price
 order by i_category
         ,i_class
         ,i_item_id
         ,i_item_desc
         ,revenueratio
limit 100
"""

QUERIES["q25"] = r"""
select
 i_item_id
 ,i_item_desc
 ,s_store_id
 ,s_store_name
 ,sum(ss_net_profit) as store_sales_profit
 ,sum(sr_net_loss) as store_returns_loss
 ,sum(cs_net_profit) as catalog_sales_profit
 from
 store_sales
 ,store_returns
 ,catalog_sales
 ,date_dim d1
 ,date_dim d2
 ,date_dim d3
 ,store
 ,item
 where
 d1.d_moy = 4
 and d1.d_year = 2002
 and d1.d_date_sk = ss_sold_date_sk
 and i_item_sk = ss_item_sk
 and s_store_sk = ss_store_sk
 and ss_customer_sk = sr_customer_sk
 and ss_item_sk = sr_item_sk
 and ss_ticket_number = sr_ticket_number
 and sr_returned_date_sk = d2.d_date_sk
 and d2.d_moy               between 4 and  10
 and d2.d_year              = 2002
 and sr_customer_sk = cs_bill_customer_sk
 and sr_item_sk = cs_item_sk
 and cs_sold_date_sk = d3.d_date_sk
 and d3.d_moy               between 4 and  10
 and d3.d_year              = 2002
 group by
 i_item_id
 ,i_item_desc
 ,s_store_id
 ,s_store_name
 order by
 i_item_id
 ,i_item_desc
 ,s_store_id
 ,s_store_name
 limit 100
"""

QUERIES["q29"] = r"""
select
     i_item_id
    ,i_item_desc
    ,s_store_id
    ,s_store_name
    ,stddev_samp(ss_quantity)        as store_sales_quantity
    ,stddev_samp(sr_return_quantity) as store_returns_quantity
    ,stddev_samp(cs_quantity)        as catalog_sales_quantity
 from
    store_sales
   ,store_returns
   ,catalog_sales
   ,date_dim             d1
   ,date_dim             d2
   ,date_dim             d3
   ,store
   ,item
 where
     d1.d_moy               = 4
 and d1.d_year              = 1998
 and d1.d_date_sk           = ss_sold_date_sk
 and i_item_sk              = ss_item_sk
 and s_store_sk             = ss_store_sk
 and ss_customer_sk         = sr_customer_sk
 and ss_item_sk             = sr_item_sk
 and ss_ticket_number       = sr_ticket_number
 and sr_returned_date_sk    = d2.d_date_sk
 and d2.d_moy               between 4 and  4 + 3
 and d2.d_year              = 1998
 and sr_customer_sk         = cs_bill_customer_sk
 and sr_item_sk             = cs_item_sk
 and cs_sold_date_sk        = d3.d_date_sk
 and d3.d_year              in (1998,1998+1,1998+2)
 group by
    i_item_id
   ,i_item_desc
   ,s_store_id
   ,s_store_name
 order by
    i_item_id
   ,i_item_desc
   ,s_store_id
   ,s_store_name
 limit 100
"""

QUERIES["q34"] = r"""
select c_last_name
       ,c_first_name
       ,c_salutation
       ,c_preferred_cust_flag
       ,ss_ticket_number
       ,cnt from
   (select ss_ticket_number
          ,ss_customer_sk
          ,count(*) cnt
    from store_sales,date_dim,store,household_demographics
    where store_sales.ss_sold_date_sk = date_dim.d_date_sk
    and store_sales.ss_store_sk = store.s_store_sk
    and store_sales.ss_hdemo_sk = household_demographics.hd_demo_sk
    and (date_dim.d_dom between 1 and 3 or date_dim.d_dom between 25 and 28)
    and (household_demographics.hd_buy_potential = '>10000' or
         household_demographics.hd_buy_potential = '5001-10000')
    and household_demographics.hd_vehicle_count > 0
    and (case when household_demographics.hd_vehicle_count > 0
	then household_demographics.hd_dep_count/ household_demographics.hd_vehicle_count
	else null
	end)  > 1.2
    and date_dim.d_year in (1999,1999+1,1999+2)
    and store.s_county in ('Jefferson Davis Parish','Levy County','Coal County','Oglethorpe County',
                           'Mobile County','Gage County','Richland County','Gogebic County')
    group by ss_ticket_number,ss_customer_sk) dn,customer
    where ss_customer_sk = c_customer_sk
      and cnt between 15 and 20
    order by c_last_name,c_first_name,c_salutation,c_preferred_cust_flag desc, ss_ticket_number
"""

QUERIES["q45"] = r"""
select  ca_zip, ca_county, sum(ws_sales_price)
 from web_sales, customer, customer_address, date_dim, item
 where ws_bill_customer_sk = c_customer_sk
 	and c_current_addr_sk = ca_address_sk
 	and ws_item_sk = i_item_sk
 	and ( substr(ca_zip,1,5) in ('85669', '86197','88274','83405','86475', '85392', '85460', '80348', '81792')
 	      or
 	      i_item_id in (select i_item_id
                             from item
                             where i_item_sk in (2, 3, 5, 7, 11, 13, 17, 19, 23, 29)
                             )
 	    )
 	and ws_sold_date_sk = d_date_sk
 	and d_qoy = 2 and d_year = 1999
 group by ca_zip, ca_county
 order by ca_zip, ca_county
 limit 100
"""

QUERIES["q48"] = r"""
select sum (ss_quantity)
 from store_sales, store, customer_demographics, customer_address, date_dim
 where s_store_sk = ss_store_sk
 and  ss_sold_date_sk = d_date_sk and d_year = 1999
 and
 (
  (
   cd_demo_sk = ss_cdemo_sk
   and
   cd_marital_status = 'D'
   and
   cd_education_status = 'College'
   and
   ss_sales_price between 100.00 and 150.00
   )
 or
  (
  cd_demo_sk = ss_cdemo_sk
   and
   cd_marital_status = 'W'
   and
   cd_education_status = 'Secondary'
   and
   ss_sales_price between 50.00 and 100.00
  )
 or
 (
  cd_demo_sk = ss_cdemo_sk
  and
   cd_marital_status = 'M'
   and
   cd_education_status = '2 yr Degree'
   and
   ss_sales_price between 150.00 and 200.00
 )
 )
 and
 (
  (
  ss_addr_sk = ca_address_sk
  and
  ca_country = 'United States'
  and
  ca_state in ('NE', 'IA', 'NY')
  and ss_net_profit between 0 and 2000
  )
 or
  (ss_addr_sk = ca_address_sk
  and
  ca_country = 'United States'
  and
  ca_state in ('IN', 'TN', 'OH')
  and ss_net_profit between 150 and 3000
  )
 or
  (ss_addr_sk = ca_address_sk
  and
  ca_country = 'United States'
  and
  ca_state in ('KS', 'CA', 'CO')
  and ss_net_profit between 50 and 25000
  )
 )
"""

QUERIES["q50"] = r"""
select
   s_store_name
  ,s_company_id
  ,s_street_number
  ,s_street_name
  ,s_street_type
  ,s_suite_number
  ,s_city
  ,s_county
  ,s_state
  ,s_zip
  ,sum(case when (sr_returned_date_sk - ss_sold_date_sk <= 30 ) then 1 else 0 end)  as `30 days`
  ,sum(case when (sr_returned_date_sk - ss_sold_date_sk > 30) and
                 (sr_returned_date_sk - ss_sold_date_sk <= 60) then 1 else 0 end )  as `31-60 days`
  ,sum(case when (sr_returned_date_sk - ss_sold_date_sk > 60) and
                 (sr_returned_date_sk - ss_sold_date_sk <= 90) then 1 else 0 end)  as `61-90 days`
  ,sum(case when (sr_returned_date_sk - ss_sold_date_sk > 90) and
                 (sr_returned_date_sk - ss_sold_date_sk <= 120) then 1 else 0 end)  as `91-120 days`
  ,sum(case when (sr_returned_date_sk - ss_sold_date_sk  > 120) then 1 else 0 end)  as `>120 days`
from
   store_sales
  ,store_returns
  ,store
  ,date_dim d1
  ,date_dim d2
where
    d2.d_year = 1999
and d2.d_moy  = 9
and ss_ticket_number = sr_ticket_number
and ss_item_sk = sr_item_sk
and ss_sold_date_sk   = d1.d_date_sk
and sr_returned_date_sk   = d2.d_date_sk
and ss_customer_sk = sr_customer_sk
and ss_store_sk = s_store_sk
group by
   s_store_name
  ,s_company_id
  ,s_street_number
  ,s_street_name
  ,s_street_type
  ,s_suite_number
  ,s_city
  ,s_county
  ,s_state
  ,s_zip
order by s_store_name
        ,s_company_id
        ,s_street_number
        ,s_street_name
        ,s_street_type
        ,s_suite_number
        ,s_city
        ,s_county
        ,s_state
        ,s_zip
limit 100
"""

QUERIES["q53"] = r"""
select  * from
(select i_manufact_id,
sum(ss_sales_price) sum_sales,
avg(sum(ss_sales_price)) over (partition by i_manufact_id) avg_quarterly_sales
from item, store_sales, date_dim, store
where ss_item_sk = i_item_sk and
ss_sold_date_sk = d_date_sk and
ss_store_sk = s_store_sk and
d_month_seq in (1218,1218+1,1218+2,1218+3,1218+4,1218+5,1218+6,1218+7,1218+8,1218+9,1218+10,1218+11) and
((i_category in ('Books','Children','Electronics') and
i_class in ('personal','portable','reference','self-help') and
i_brand in ('scholaramalgamalg #14','scholaramalgamalg #7',
		'exportiunivamalg #9','scholaramalgamalg #9'))
or(i_category in ('Women','Music','Men') and
i_class in ('accessories','classical','fragrances','pants') and
i_brand in ('amalgimporto #1','edu packscholar #1','exportiimporto #1',
		'importoamalg #1')))
group by i_manufact_id, d_qoy ) tmp1
where case when avg_quarterly_sales > 0
	then abs (sum_sales - avg_quarterly_sales)/ avg_quarterly_sales
	else null end > 0.1
order by avg_quarterly_sales,
	 sum_sales,
	 i_manufact_id
limit 100
"""

QUERIES["q63"] = r"""
select  *
from (select i_manager_id
             ,sum(ss_sales_price) sum_sales
             ,avg(sum(ss_sales_price)) over (partition by i_manager_id) avg_monthly_sales
      from item
          ,store_sales
          ,date_dim
          ,store
      where ss_item_sk = i_item_sk
        and ss_sold_date_sk = d_date_sk
        and ss_store_sk = s_store_sk
        and d_month_seq in (1205,1205+1,1205+2,1205+3,1205+4,1205+5,1205+6,1205+7,1205+8,1205+9,1205+10,1205+11)
        and ((    i_category in ('Books','Children','Electronics')
              and i_class in ('personal','portable','reference','self-help')
              and i_brand in ('scholaramalgamalg #14','scholaramalgamalg #7',
		                  'exportiunivamalg #9','scholaramalgamalg #9'))
           or(    i_category in ('Women','Music','Men')
              and i_class in ('accessories','classical','fragrances','pants')
              and i_brand in ('amalgimporto #1','edu packscholar #1','exportiimporto #1',
		                 'importoamalg #1')))
group by i_manager_id, d_moy) tmp1
where case when avg_monthly_sales > 0 then abs (sum_sales - avg_monthly_sales) / avg_monthly_sales else null end > 0.1
order by i_manager_id
        ,avg_monthly_sales
        ,sum_sales
limit 100
"""

QUERIES["q65"] = r"""
select
	s_store_name,
	i_item_desc,
	sc.revenue,
	i_current_price,
	i_wholesale_cost,
	i_brand
 from store, item,
     (select ss_store_sk, avg(revenue) as ave
 	from
 	    (select  ss_store_sk, ss_item_sk,
 		     sum(ss_sales_price) as revenue
 		from store_sales, date_dim
 		where ss_sold_date_sk = d_date_sk and d_month_seq between 1208 and 1208+11
 		group by ss_store_sk, ss_item_sk) sa
 	group by ss_store_sk) sb,
     (select  ss_store_sk, ss_item_sk, sum(ss_sales_price) as revenue
 	from store_sales, date_dim
 	where ss_sold_date_sk = d_date_sk and d_month_seq between 1208 and 1208+11
 	group by ss_store_sk, ss_item_sk) sc
 where sb.ss_store_sk = sc.ss_store_sk and
       sc.revenue <= 0.1 * sb.ave and
       s_store_sk = sc.ss_store_sk and
       i_item_sk = sc.ss_item_sk
 order by s_store_name, i_item_desc
limit 100
"""

QUERIES["q68"] = r"""
select  c_last_name
       ,c_first_name
       ,ca_city
       ,bought_city
       ,ss_ticket_number
       ,extended_price
       ,extended_tax
       ,list_price
 from (select ss_ticket_number
             ,ss_customer_sk
             ,ca_city bought_city
             ,sum(ss_ext_sales_price) extended_price
             ,sum(ss_ext_list_price) list_price
             ,sum(ss_ext_tax) extended_tax
       from store_sales
           ,date_dim
           ,store
           ,household_demographics
           ,customer_address
       where store_sales.ss_sold_date_sk = date_dim.d_date_sk
         and store_sales.ss_store_sk = store.s_store_sk
        and store_sales.ss_hdemo_sk = household_demographics.hd_demo_sk
        and store_sales.ss_addr_sk = customer_address.ca_address_sk
        and date_dim.d_dom between 1 and 2
        and (household_demographics.hd_dep_count = 1 or
             household_demographics.hd_vehicle_count= -1)
        and date_dim.d_year in (1998,1998+1,1998+2)
        and store.s_city in ('Bethel','Summit')
       group by ss_ticket_number
               ,ss_customer_sk
               ,ss_addr_sk,ca_city) dn
      ,customer
      ,customer_address current_addr
 where ss_customer_sk = c_customer_sk
   and customer.c_current_addr_sk = current_addr.ca_address_sk
   and current_addr.ca_city <> bought_city
 order by c_last_name
         ,ss_ticket_number
 limit 100
"""

QUERIES["q73"] = r"""
select c_last_name
       ,c_first_name
       ,c_salutation
       ,c_preferred_cust_flag
       ,ss_ticket_number
       ,cnt from
   (select ss_ticket_number
          ,ss_customer_sk
          ,count(*) cnt
    from store_sales,date_dim,store,household_demographics
    where store_sales.ss_sold_date_sk = date_dim.d_date_sk
    and store_sales.ss_store_sk = store.s_store_sk
    and store_sales.ss_hdemo_sk = household_demographics.hd_demo_sk
    and date_dim.d_dom between 1 and 2
    and (household_demographics.hd_buy_potential = '501-1000' or
         household_demographics.hd_buy_potential = 'Unknown')
    and household_demographics.hd_vehicle_count > 0
    and case when household_demographics.hd_vehicle_count > 0 then
             household_demographics.hd_dep_count/ household_demographics.hd_vehicle_count else null end > 1
    and date_dim.d_year in (1999,1999+1,1999+2)
    and store.s_county in ('Franklin Parish','Ziebach County','Luce County','Williamson County')
    group by ss_ticket_number,ss_customer_sk) dj,customer
    where ss_customer_sk = c_customer_sk
      and cnt between 1 and 5
    order by cnt desc, c_last_name asc
"""

QUERIES["q79"] = r"""
select
  c_last_name,c_first_name,substr(s_city,1,30),ss_ticket_number,amt,profit
  from
   (select ss_ticket_number
          ,ss_customer_sk
          ,store.s_city
          ,sum(ss_coupon_amt) amt
          ,sum(ss_net_profit) profit
    from store_sales,date_dim,store,household_demographics
    where store_sales.ss_sold_date_sk = date_dim.d_date_sk
    and store_sales.ss_store_sk = store.s_store_sk
    and store_sales.ss_hdemo_sk = household_demographics.hd_demo_sk
    and (household_demographics.hd_dep_count = 0 or household_demographics.hd_vehicle_count > 0)
    and date_dim.d_dow = 1
    and date_dim.d_year in (2000,2000+1,2000+2)
    and store.s_number_employees between 200 and 295
    group by ss_ticket_number,ss_customer_sk,ss_addr_sk,store.s_city) ms,customer
    where ss_customer_sk = c_customer_sk
 order by c_last_name,c_first_name,substr(s_city,1,30), profit
limit 100
"""

QUERIES["q88"] = r"""
select  *
from
 (select count(*) h8_30_to_9
 from store_sales, household_demographics , time_dim, store
 where ss_sold_time_sk = time_dim.t_time_sk
     and ss_hdemo_sk = household_demographics.hd_demo_sk
     and ss_store_sk = s_store_sk
     and time_dim.t_hour = 8
     and time_dim.t_minute >= 30
     and ((household_demographics.hd_dep_count = 1 and household_demographics.hd_vehicle_count<=1+2) or
          (household_demographics.hd_dep_count = 2 and household_demographics.hd_vehicle_count<=2+2) or
          (household_demographics.hd_dep_count = 0 and household_demographics.hd_vehicle_count<=0+2))
     and store.s_store_name = 'ese') s1,
 (select count(*) h9_to_9_30
 from store_sales, household_demographics , time_dim, store
 where ss_sold_time_sk = time_dim.t_time_sk
     and ss_hdemo_sk = household_demographics.hd_demo_sk
     and ss_store_sk = s_store_sk
     and time_dim.t_hour = 9
     and time_dim.t_minute < 30
     and ((household_demographics.hd_dep_count = 1 and household_demographics.hd_vehicle_count<=1+2) or
          (household_demographics.hd_dep_count = 2 and household_demographics.hd_vehicle_count<=2+2) or
          (household_demographics.hd_dep_count = 0 and household_demographics.hd_vehicle_count<=0+2))
     and store.s_store_name = 'ese') s2,
 (select count(*) h9_30_to_10
 from store_sales, household_demographics , time_dim, store
 where ss_sold_time_sk = time_dim.t_time_sk
     and ss_hdemo_sk = household_demographics.hd_demo_sk
     and ss_store_sk = s_store_sk
     and time_dim.t_hour = 9
     and time_dim.t_minute >= 30
     and ((household_demographics.hd_dep_count = 1 and household_demographics.hd_vehicle_count<=1+2) or
          (household_demographics.hd_dep_count = 2 and household_demographics.hd_vehicle_count<=2+2) or
          (household_demographics.hd_dep_count = 0 and household_demographics.hd_vehicle_count<=0+2))
     and store.s_store_name = 'ese') s3,
 (select count(*) h10_to_10_30
 from store_sales, household_demographics , time_dim, store
 where ss_sold_time_sk = time_dim.t_time_sk
     and ss_hdemo_sk = household_demographics.hd_demo_sk
     and ss_store_sk = s_store_sk
     and time_dim.t_hour = 10
     and time_dim.t_minute < 30
     and ((household_demographics.hd_dep_count = 1 and household_demographics.hd_vehicle_count<=1+2) or
          (household_demographics.hd_dep_count = 2 and household_demographics.hd_vehicle_count<=2+2) or
          (household_demographics.hd_dep_count = 0 and household_demographics.hd_vehicle_count<=0+2))
     and store.s_store_name = 'ese') s4,
 (select count(*) h10_30_to_11
 from store_sales, household_demographics , time_dim, store
 where ss_sold_time_sk = time_dim.t_time_sk
     and ss_hdemo_sk = household_demographics.hd_demo_sk
     and ss_store_sk = s_store_sk
     and time_dim.t_hour = 10
     and time_dim.t_minute >= 30
     and ((household_demographics.hd_dep_count = 1 and household_demographics.hd_vehicle_count<=1+2) or
          (household_demographics.hd_dep_count = 2 and household_demographics.hd_vehicle_count<=2+2) or
          (household_demographics.hd_dep_count = 0 and household_demographics.hd_vehicle_count<=0+2))
     and store.s_store_name = 'ese') s5,
 (select count(*) h11_to_11_30
 from store_sales, household_demographics , time_dim, store
 where ss_sold_time_sk = time_dim.t_time_sk
     and ss_hdemo_sk = household_demographics.hd_demo_sk
     and ss_store_sk = s_store_sk
     and time_dim.t_hour = 11
     and time_dim.t_minute < 30
     and ((household_demographics.hd_dep_count = 1 and household_demographics.hd_vehicle_count<=1+2) or
          (household_demographics.hd_dep_count = 2 and household_demographics.hd_vehicle_count<=2+2) or
          (household_demographics.hd_dep_count = 0 and household_demographics.hd_vehicle_count<=0+2))
     and store.s_store_name = 'ese') s6,
 (select count(*) h11_30_to_12
 from store_sales, household_demographics , time_dim, store
 where ss_sold_time_sk = time_dim.t_time_sk
     and ss_hdemo_sk = household_demographics.hd_demo_sk
     and ss_store_sk = s_store_sk
     and time_dim.t_hour = 11
     and time_dim.t_minute >= 30
     and ((household_demographics.hd_dep_count = 1 and household_demographics.hd_vehicle_count<=1+2) or
          (household_demographics.hd_dep_count = 2 and household_demographics.hd_vehicle_count<=2+2) or
          (household_demographics.hd_dep_count = 0 and household_demographics.hd_vehicle_count<=0+2))
     and store.s_store_name = 'ese') s7,
 (select count(*) h12_to_12_30
 from store_sales, household_demographics , time_dim, store
 where ss_sold_time_sk = time_dim.t_time_sk
     and ss_hdemo_sk = household_demographics.hd_demo_sk
     and ss_store_sk = s_store_sk
     and time_dim.t_hour = 12
     and time_dim.t_minute < 30
     and ((household_demographics.hd_dep_count = 1 and household_demographics.hd_vehicle_count<=1+2) or
          (household_demographics.hd_dep_count = 2 and household_demographics.hd_vehicle_count<=2+2) or
          (household_demographics.hd_dep_count = 0 and household_demographics.hd_vehicle_count<=0+2))
     and store.s_store_name = 'ese') s8
"""

QUERIES["q89"] = r"""
select  *
from(
select i_category, i_class, i_brand,
       s_store_name, s_company_name,
       d_moy,
       sum(ss_sales_price) sum_sales,
       avg(sum(ss_sales_price)) over
         (partition by i_category, i_brand, s_store_name, s_company_name)
         avg_monthly_sales
from item, store_sales, date_dim, store
where ss_item_sk = i_item_sk and
      ss_sold_date_sk = d_date_sk and
      ss_store_sk = s_store_sk and
      d_year in (2001) and
        ((i_category in ('Women','Music','Home') and
          i_class in ('fragrances','pop','bedding')
         )
      or (i_category in ('Books','Men','Children') and
          i_class in ('home repair','sports-apparel','infants')
        ))
group by i_category, i_class, i_brand,
         s_store_name, s_company_name, d_moy) tmp1
where case when (avg_monthly_sales <> 0) then (abs(sum_sales - avg_monthly_sales) / avg_monthly_sales) else null end > 0.1
order by sum_sales - avg_monthly_sales, s_store_name
limit 100
"""

QUERIES["q90"] = r"""
select  cast(amc as decimal(15,4))/cast(pmc as decimal(15,4)) am_pm_ratio
 from ( select count(*) amc
       from web_sales, household_demographics , time_dim, web_page
       where ws_sold_time_sk = time_dim.t_time_sk
         and ws_ship_hdemo_sk = household_demographics.hd_demo_sk
         and ws_web_page_sk = web_page.wp_web_page_sk
         and time_dim.t_hour between 8 and 8+1
         and household_demographics.hd_dep_count = 4
         and web_page.wp_char_count between 5000 and 5200) at,
      ( select count(*) pmc
       from web_sales, household_demographics , time_dim, web_page
       where ws_sold_time_sk = time_dim.t_time_sk
         and ws_ship_hdemo_sk = household_demographics.hd_demo_sk
         and ws_web_page_sk = web_page.wp_web_page_sk
         and time_dim.t_hour between 19 and 19+1
         and household_demographics.hd_dep_count = 4
         and web_page.wp_char_count between 5000 and 5200) pt
 order by am_pm_ratio
 limit 100
"""

QUERIES["q98"] = r"""
select i_item_id
      ,i_item_desc
      ,i_category
      ,i_class
      ,i_current_price
      ,sum(ss_ext_sales_price) as itemrevenue
      ,sum(ss_ext_sales_price)*100/sum(sum(ss_ext_sales_price)) over
          (partition by i_class) as revenueratio
from
	store_sales
    	,item
    	,date_dim
where
	ss_item_sk = i_item_sk
  	and i_category in ('Jewelry', 'Home', 'Shoes')
  	and ss_sold_date_sk = d_date_sk
	and d_date between cast('2001-04-12' as date)
				and (cast('2001-04-12' as date) + interval 30 days)
group by
	i_item_id
        ,i_item_desc
        ,i_category
        ,i_class
        ,i_current_price
order by
	i_category
        ,i_class
        ,i_item_id
        ,i_item_desc
        ,revenueratio
"""

QUERIES["q99"] = r"""
select
   substr(w_warehouse_name,1,20)
  ,sm_type
  ,cc_name
  ,sum(case when (cs_ship_date_sk - cs_sold_date_sk <= 30 ) then 1 else 0 end)  as `30 days`
  ,sum(case when (cs_ship_date_sk - cs_sold_date_sk > 30) and
                 (cs_ship_date_sk - cs_sold_date_sk <= 60) then 1 else 0 end )  as `31-60 days`
  ,sum(case when (cs_ship_date_sk - cs_sold_date_sk > 60) and
                 (cs_ship_date_sk - cs_sold_date_sk <= 90) then 1 else 0 end)  as `61-90 days`
  ,sum(case when (cs_ship_date_sk - cs_sold_date_sk > 90) and
                 (cs_ship_date_sk - cs_sold_date_sk <= 120) then 1 else 0 end)  as `91-120 days`
  ,sum(case when (cs_ship_date_sk - cs_sold_date_sk  > 120) then 1 else 0 end)  as `>120 days`
from
   catalog_sales
  ,warehouse
  ,ship_mode
  ,call_center
  ,date_dim
where
    d_month_seq between 1203 and 1203 + 11
and cs_ship_date_sk   = d_date_sk
and cs_warehouse_sk   = w_warehouse_sk
and cs_ship_mode_sk   = sm_ship_mode_sk
and cs_call_center_sk = cc_call_center_sk
group by
   substr(w_warehouse_name,1,20)
  ,sm_type
  ,cc_name
order by substr(w_warehouse_name,1,20)
        ,sm_type
        ,cc_name
limit 100
"""

# --- added in round 4 (second wave): CTEs, UNION [ALL], correlated subqueries (verbatim) ---

QUERIES["q1"] = r"""
with customer_total_return as
(select sr_customer_sk as ctr_customer_sk
,sr_store_sk as ctr_store_sk
,sum(SR_FEE) as ctr_total_return
from store_returns
,date_dim
where sr_returned_date_sk = d_date_sk
and d_year =2000
group by sr_customer_sk
,sr_store_sk)
 select  c_customer_id
from customer_total_return ctr1
,store
,customer
where ctr1.ctr_total_return > (select avg(ctr_total_return)*1.2
from customer_total_return ctr2
where ctr1.ctr_store_sk = ctr2.ctr_store_sk)
and s_store_sk = ctr1.ctr_store_sk
and s_state = 'TN'
and ctr1.ctr_customer_sk = c_customer_sk
order by c_customer_id
limit 100
"""

QUERIES["q6"] = r"""
select  a.ca_state state, count(*) cnt
 from customer_address a
     ,customer c
     ,store_sales s
     ,date_dim d
     ,item i
 where       a.ca_address_sk = c.c_current_addr_sk
 	and c.c_customer_sk = s.ss_customer_sk
 	and s.ss_sold_date_sk = d.d_date_sk
 	and s.ss_item_sk = i.i_item_sk
 	and d.d_month_seq =
 	     (select distinct (d_month_seq)
 	      from date_dim
               where d_year = 2002
 	        and d_moy = 3 )
 	and i.i_current_price > 1.2 *
             (select avg(j.i_current_price)
 	     from item j
 	     where j.i_category = i.i_category)
 group by a.ca_state
 having count(*) >= 10
 order by cnt, a.ca_state
 limit 100
"""

QUERIES["q30"] = r"""
with customer_total_return as
 (select wr_returning_customer_sk as ctr_customer_sk
        ,ca_state as ctr_state,
 	sum(wr_return_amt) as ctr_total_return
 from web_returns
     ,date_dim
     ,customer_address
 where wr_returned_date_sk = d_date_sk
   and d_year =2000
   and wr_returning_addr_sk = ca_address_sk
 group by wr_returning_customer_sk
         ,ca_state)
  select  c_customer_id,c_salutation,c_first_name,c_last_name,c_preferred_cust_flag
       ,c_birth_day,c_birth_month,c_birth_year,c_birth_country,c_login,c_email_address
       ,c_last_review_date,ctr_total_return
 from customer_total_return ctr1
     ,customer_address
     ,customer
 where ctr1.ctr_total_return > (select avg(ctr_total_return)*1.2
 			  from customer_total_return ctr2
                  	  where ctr1.ctr_state = ctr2.ctr_state)
       and ca_address_sk = c_current_addr_sk
       and ca_state = 'GA'
       and ctr1.ctr_customer_sk = c_customer_sk
 order by c_customer_id,c_salutation,c_first_name,c_last_name,c_preferred_cust_flag
                  ,c_birth_day,c_birth_month,c_birth_year,c_birth_country,c_login,c_email_address
                  ,c_last_review_date,ctr_total_return
limit 100
"""

QUERIES["q32"] = r"""
select  sum(cs_ext_discount_amt)  as `excess discount amount`
from
   catalog_sales
   ,item
   ,date_dim
where
i_manufact_id = 948
and i_item_sk = cs_item_sk
and d_date between '1998-02-03' and
        (cast('1998-02-03' as date) + INTERVAL 90 days)
and d_date_sk = cs_sold_date_sk
and cs_ext_discount_amt
     > (
         select
            1.3 * avg(cs_ext_discount_amt)
         from
            catalog_sales
           ,date_dim
         where
              cs_item_sk = i_item_sk
          and d_date between '1998-02-03' and
                             (cast('1998-02-03' as date) + INTERVAL 90 days)
          and d_date_sk = cs_sold_date_sk
      )
limit 100
"""

QUERIES["q47"] = r"""
with v1 as(
 select i_category, i_brand,
        s_store_name, s_company_name,
        d_year, d_moy,
        sum(ss_sales_price) sum_sales,
        avg(sum(ss_sales_price)) over
          (partition by i_category, i_brand,
                     s_store_name, s_company_name, d_year)
          avg_monthly_sales,
        rank() over
          (partition by i_category, i_brand,
                     s_store_name, s_company_name
           order by d_year, d_moy) rn
 from item, store_sales, date_dim, store
 where ss_item_sk = i_item_sk and
       ss_sold_date_sk = d_date_sk and
       ss_store_sk = s_store_sk and
       (
         d_year = 2001 or
         ( d_year = 2001-1 and d_moy =12) or
         ( d_year = 2001+1 and d_moy =1)
       )
 group by i_category, i_brand,
          s_store_name, s_company_name,
          d_year, d_moy),
 v2 as(
 select v1.s_company_name
        ,v1.d_year, v1.d_moy
        ,v1.avg_monthly_sales
        ,v1.sum_sales, v1_lag.sum_sales psum, v1_lead.sum_sales nsum
 from v1, v1 v1_lag, v1 v1_lead
 where v1.i_category = v1_lag.i_category and
       v1.i_category = v1_lead.i_category and
       v1.i_brand = v1_lag.i_brand and
       v1.i_brand = v1_lead.i_brand and
       v1.s_store_name = v1_lag.s_store_name and
       v1.s_store_name = v1_lead.s_store_name and
       v1.s_company_name = v1_lag.s_company_name and
       v1.s_company_name = v1_lead.s_company_name and
       v1.rn = v1_lag.rn + 1 and
       v1.rn = v1_lead.rn - 1)
  select  *
 from v2
 where  d_year = 2001 and
        avg_monthly_sales > 0 and
        case when avg_monthly_sales > 0 then abs(sum_sales - avg_monthly_sales) / avg_monthly_sales else null end > 0.1
 order by sum_sales - avg_monthly_sales, avg_monthly_sales
 limit 100
"""

QUERIES["q51"] = r"""
WITH web_v1 as (
select
  ws_item_sk item_sk, d_date,
  sum(sum(ws_sales_price))
      over (partition by ws_item_sk order by d_date rows between unbounded preceding and current row) cume_sales
from web_sales
    ,date_dim
where ws_sold_date_sk=d_date_sk
  and d_month_seq between 1176 and 1176+11
  and ws_item_sk is not NULL
group by ws_item_sk, d_date),
store_v1 as (
select
  ss_item_sk item_sk, d_date,
  sum(sum(ss_sales_price))
      over (partition by ss_item_sk order by d_date rows between unbounded preceding and current row) cume_sales
from store_sales
    ,date_dim
where ss_sold_date_sk=d_date_sk
  and d_month_seq between 1176 and 1176+11
  and ss_item_sk is not NULL
group by ss_item_sk, d_date)
 select  *
from (select item_sk
     ,d_date
     ,web_sales
     ,store_sales
     ,max(web_sales)
         over (partition by item_sk order by d_date rows between unbounded preceding and current row) web_cumulative
     ,max(store_sales)
         over (partition by item_sk order by d_date rows between unbounded preceding and current row) store_cumulative
     from (select case when web.item_sk is not null then web.item_sk else store.item_sk end item_sk
                 ,case when web.d_date is not null then web.d_date else store.d_date end d_date
                 ,web.cume_sales web_sales
                 ,store.cume_sales store_sales
           from web_v1 web full outer join store_v1 store on (web.item_sk = store.item_sk
                                                          and web.d_date = store.d_date)
          )x )y
where web_cumulative > store_cumulative
order by item_sk
        ,d_date
limit 100
"""

QUERIES["q57"] = r"""
with v1 as(
 select i_category, i_brand,
        cc_name,
        d_year, d_moy,
        sum(cs_sales_price) sum_sales,
        avg(sum(cs_sales_price)) over
          (partition by i_category, i_brand,
                     cc_name, d_year)
          avg_monthly_sales,
        rank() over
          (partition by i_category, i_brand,
                     cc_name
           order by d_year, d_moy) rn
 from item, catalog_sales, date_dim, call_center
 where cs_item_sk = i_item_sk and
       cs_sold_date_sk = d_date_sk and
       cc_call_center_sk= cs_call_center_sk and
       (
         d_year = 2001 or
         ( d_year = 2001-1 and d_moy =12) or
         ( d_year = 2001+1 and d_moy =1)
       )
 group by i_category, i_brand,
          cc_name , d_year, d_moy),
 v2 as(
 select v1.i_category, v1.i_brand, v1.cc_name
        ,v1.d_year, v1.d_moy
        ,v1.avg_monthly_sales
        ,v1.sum_sales, v1_lag.sum_sales psum, v1_lead.sum_sales nsum
 from v1, v1 v1_lag, v1 v1_lead
 where v1.i_category = v1_lag.i_category and
       v1.i_category = v1_lead.i_category and
       v1.i_brand = v1_lag.i_brand and
       v1.i_brand = v1_lead.i_brand and
       v1. cc_name = v1_lag. cc_name and
       v1. cc_name = v1_lead. cc_name and
       v1.rn = v1_lag.rn + 1 and
       v1.rn = v1_lead.rn - 1)
  select  *
 from v2
 where  d_year = 2001 and
        avg_monthly_sales > 0 and
        case when avg_monthly_sales > 0 then abs(sum_sales - avg_monthly_sales) / avg_monthly_sales else null end > 0.1
 order by sum_sales - avg_monthly_sales, avg_monthly_sales
 limit 100
"""

QUERIES["q59"] = r"""
with wss as
 (select d_week_seq,
        ss_store_sk,
        sum(case when (d_day_name='Sunday') then ss_sales_price else null end) sun_sales,
        sum(case when (d_day_name='Monday') then ss_sales_price else null end) mon_sales,
        sum(case when (d_day_name='Tuesday') then ss_sales_price else  null end) tue_sales,
        sum(case when (d_day_name='Wednesday') then ss_sales_price else null end) wed_sales,
        sum(case when (d_day_name='Thursday') then ss_sales_price else null end) thu_sales,
        sum(case when (d_day_name='Friday') then ss_sales_price else null end) fri_sales,
        sum(case when (d_day_name='Saturday') then ss_sales_price else null end) sat_sales
 from store_sales,date_dim
 where d_date_sk = ss_sold_date_sk
 group by d_week_seq,ss_store_sk
 )
  select  s_store_name1,s_store_id1,d_week_seq1
       ,sun_sales1/sun_sales2,mon_sales1/mon_sales2
       ,tue_sales1/tue_sales2,wed_sales1/wed_sales2,thu_sales1/thu_sales2
       ,fri_sales1/fri_sales2,sat_sales1/sat_sales2
 from
 (select s_store_name s_store_name1,wss.d_week_seq d_week_seq1
        ,s_store_id s_store_id1,sun_sales sun_sales1
        ,mon_sales mon_sales1,tue_sales tue_sales1
        ,wed_sales wed_sales1,thu_sales thu_sales1
        ,fri_sales fri_sales1,sat_sales sat_sales1
  from wss,store,date_dim d
  where d.d_week_seq = wss.d_week_seq and
        ss_store_sk = s_store_sk and
        d_month_seq between 1199 and 1199 + 11) y,
 (select s_store_name s_store_name2,wss.d_week_seq d_week_seq2
        ,s_store_id s_store_id2,sun_sales sun_sales2
        ,mon_sales mon_sales2,tue_sales tue_sales2
        ,wed_sales wed_sales2,thu_sales thu_sales2
        ,fri_sales fri_sales2,sat_sales sat_sales2
  from wss,store,date_dim d
  where d.d_week_seq = wss.d_week_seq and
        ss_store_sk = s_store_sk and
        d_month_seq between 1199+ 12 and 1199 + 23) x
 where s_store_id1=s_store_id2
   and d_week_seq1=d_week_seq2-52
 order by s_store_name1,s_store_id1,d_week_seq1
limit 100
"""

QUERIES["q71"] = r"""
select i_brand_id brand_id, i_brand brand,t_hour,t_minute,
 	sum(ext_price) ext_price
 from item, (select ws_ext_sales_price as ext_price,
                        ws_sold_date_sk as sold_date_sk,
                        ws_item_sk as sold_item_sk,
                        ws_sold_time_sk as time_sk
                 from web_sales,date_dim
                 where d_date_sk = ws_sold_date_sk
                   and d_moy=12
                   and d_year=1999
                 union all
                 select cs_ext_sales_price as ext_price,
                        cs_sold_date_sk as sold_date_sk,
                        cs_item_sk as sold_item_sk,
                        cs_sold_time_sk as time_sk
                 from catalog_sales,date_dim
                 where d_date_sk = cs_sold_date_sk
                   and d_moy=12
                   and d_year=1999
                 union all
                 select ss_ext_sales_price as ext_price,
                        ss_sold_date_sk as sold_date_sk,
                        ss_item_sk as sold_item_sk,
                        ss_sold_time_sk as time_sk
                 from store_sales,date_dim
                 where d_date_sk = ss_sold_date_sk
                   and d_moy=12
                   and d_year=1999
                 ) tmp,time_dim
 where
   sold_item_sk = i_item_sk
   and i_manager_id=1
   and time_sk = t_time_sk
   and (t_meal_time = 'breakfast' or t_meal_time = 'dinner')
 group by i_brand, i_brand_id,t_hour,t_minute
 order by ext_price desc, i_brand_id
"""

QUERIES["q74"] = r"""
with year_total as (
 select c_customer_id customer_id
       ,c_first_name customer_first_name
       ,c_last_name customer_last_name
       ,d_year as year
       ,max(ss_net_paid) year_total
       ,'s' sale_type
 from customer
     ,store_sales
     ,date_dim
 where c_customer_sk = ss_customer_sk
   and ss_sold_date_sk = d_date_sk
   and d_year in (2001,2001+1)
 group by c_customer_id
         ,c_first_name
         ,c_last_name
         ,d_year
 union all
 select c_customer_id customer_id
       ,c_first_name customer_first_name
       ,c_last_name customer_last_name
       ,d_year as year
       ,max(ws_net_paid) year_total
       ,'w' sale_type
 from customer
     ,web_sales
     ,date_dim
 where c_customer_sk = ws_bill_customer_sk
   and ws_sold_date_sk = d_date_sk
   and d_year in (2001,2001+1)
 group by c_customer_id
         ,c_first_name
         ,c_last_name
         ,d_year
         )
  select
        t_s_secyear.customer_id, t_s_secyear.customer_first_name, t_s_secyear.customer_last_name
 from year_total t_s_firstyear
     ,year_total t_s_secyear
     ,year_total t_w_firstyear
     ,year_total t_w_secyear
 where t_s_secyear.customer_id = t_s_firstyear.customer_id
         and t_s_firstyear.customer_id = t_w_secyear.customer_id
         and t_s_firstyear.customer_id = t_w_firstyear.customer_id
         and t_s_firstyear.sale_type = 's'
         and t_w_firstyear.sale_type = 'w'
         and t_s_secyear.sale_type = 's'
         and t_w_secyear.sale_type = 'w'
         and t_s_firstyear.year = 2001
         and t_s_secyear.year = 2001+1
         and t_w_firstyear.year = 2001
         and t_w_secyear.year = 2001+1
         and t_s_firstyear.year_total > 0
         and t_w_firstyear.year_total > 0
         and case when t_w_firstyear.year_total > 0 then t_w_secyear.year_total / t_w_firstyear.year_total else null end
           > case when t_s_firstyear.year_total > 0 then t_s_secyear.year_total / t_s_firstyear.year_total else null end
 order by 3,1,2
limit 100
"""

QUERIES["q75"] = r"""
WITH all_sales AS (
 SELECT d_year
       ,i_brand_id
       ,i_class_id
       ,i_category_id
       ,i_manufact_id
       ,SUM(sales_cnt) AS sales_cnt
       ,SUM(sales_amt) AS sales_amt
 FROM (SELECT d_year
             ,i_brand_id
             ,i_class_id
             ,i_category_id
             ,i_manufact_id
             ,cs_quantity - COALESCE(cr_return_quantity,0) AS sales_cnt
             ,cs_ext_sales_price - COALESCE(cr_return_amount,0.0) AS sales_amt
       FROM catalog_sales JOIN item ON i_item_sk=cs_item_sk
                          JOIN date_dim ON d_date_sk=cs_sold_date_sk
                          LEFT JOIN catalog_returns ON (cs_order_number=cr_order_number
                                                    AND cs_item_sk=cr_item_sk)
       WHERE i_category='Sports'
       UNION
       SELECT d_year
             ,i_brand_id
             ,i_class_id
             ,i_category_id
             ,i_manufact_id
             ,ss_quantity - COALESCE(sr_return_quantity,0) AS sales_cnt
             ,ss_ext_sales_price - COALESCE(sr_return_amt,0.0) AS sales_amt
       FROM store_sales JOIN item ON i_item_sk=ss_item_sk
                        JOIN date_dim ON d_date_sk=ss_sold_date_sk
                        LEFT JOIN store_returns ON (ss_ticket_number=sr_ticket_number
                                                AND ss_item_sk=sr_item_sk)
       WHERE i_category='Sports'
       UNION
       SELECT d_year
             ,i_brand_id
             ,i_class_id
             ,i_category_id
             ,i_manufact_id
             ,ws_quantity - COALESCE(wr_return_quantity,0) AS sales_cnt
             ,ws_ext_sales_price - COALESCE(wr_return_amt,0.0) AS sales_amt
       FROM web_sales JOIN item ON i_item_sk=ws_item_sk
                      JOIN date_dim ON d_date_sk=ws_sold_date_sk
                      LEFT JOIN web_returns ON (ws_order_number=wr_order_number
                                            AND ws_item_sk=wr_item_sk)
       WHERE i_category='Sports') sales_detail
 GROUP BY d_year, i_brand_id, i_class_id, i_category_id, i_manufact_id)
 SELECT  prev_yr.d_year AS prev_year
                          ,curr_yr.d_year AS year
                          ,curr_yr.i_brand_id
                          ,curr_yr.i_class_id
                          ,curr_yr.i_category_id
                          ,curr_yr.i_manufact_id
                          ,prev_yr.sales_cnt AS prev_yr_cnt
                          ,curr_yr.sales_cnt AS curr_yr_cnt
                          ,curr_yr.sales_cnt-prev_yr.sales_cnt AS sales_cnt_diff
                          ,curr_yr.sales_amt-prev_yr.sales_amt AS sales_amt_diff
 FROM all_sales curr_yr, all_sales prev_yr
 WHERE curr_yr.i_brand_id=prev_yr.i_brand_id
   AND curr_yr.i_class_id=prev_yr.i_class_id
   AND curr_yr.i_category_id=prev_yr.i_category_id
   AND curr_yr.i_manufact_id=prev_yr.i_manufact_id
   AND curr_yr.d_year=2001
   AND prev_yr.d_year=2001-1
   AND CAST(curr_yr.sales_cnt AS DECIMAL(17,2))/CAST(prev_yr.sales_cnt AS DECIMAL(17,2))<0.9
 ORDER BY sales_cnt_diff,sales_amt_diff
 limit 100
"""

QUERIES["q76"] = r"""
select  channel, col_name, d_year, d_qoy, i_category, COUNT(*) sales_cnt, SUM(ext_sales_price) sales_amt FROM (
        SELECT 'store' as channel, 'ss_cdemo_sk' col_name, d_year, d_qoy, i_category, ss_ext_sales_price ext_sales_price
         FROM store_sales, item, date_dim
         WHERE ss_cdemo_sk IS NULL
           AND ss_sold_date_sk=d_date_sk
           AND ss_item_sk=i_item_sk
        UNION ALL
        SELECT 'web' as channel, 'ws_ship_hdemo_sk' col_name, d_year, d_qoy, i_category, ws_ext_sales_price ext_sales_price
         FROM web_sales, item, date_dim
         WHERE ws_ship_hdemo_sk IS NULL
           AND ws_sold_date_sk=d_date_sk
           AND ws_item_sk=i_item_sk
        UNION ALL
        SELECT 'catalog' as channel, 'cs_ship_customer_sk' col_name, d_year, d_qoy, i_category, cs_ext_sales_price ext_sales_price
         FROM catalog_sales, item, date_dim
         WHERE cs_ship_customer_sk IS NULL
           AND cs_sold_date_sk=d_date_sk
           AND cs_item_sk=i_item_sk) foo
GROUP BY channel, col_name, d_year, d_qoy, i_category
ORDER BY channel, col_name, d_year, d_qoy, i_category
limit 100
"""

QUERIES["q81"] = r"""
with customer_total_return as
 (select cr_returning_customer_sk as ctr_customer_sk
        ,ca_state as ctr_state,
 	sum(cr_return_amt_inc_tax) as ctr_total_return
 from catalog_returns
     ,date_dim
     ,customer_address
 where cr_returned_date_sk = d_date_sk
   and d_year =2001
   and cr_returning_addr_sk = ca_address_sk
 group by cr_returning_customer_sk
         ,ca_state )
  select  c_customer_id,c_salutation,c_first_name,c_last_name,ca_street_number,ca_street_name
                   ,ca_street_type,ca_suite_number,ca_city,ca_county,ca_state,ca_zip,ca_country,ca_gmt_offset
                  ,ca_location_type,ctr_total_return
 from customer_total_return ctr1
     ,customer_address
     ,customer
 where ctr1.ctr_total_return > (select avg(ctr_total_return)*1.2
 			  from customer_total_return ctr2
                  	  where ctr1.ctr_state = ctr2.ctr_state)
       and ca_address_sk = c_current_addr_sk
       and ca_state = 'NC'
       and ctr1.ctr_customer_sk = c_customer_sk
 order by c_customer_id,c_salutation,c_first_name,c_last_name,ca_street_number,ca_street_name
                   ,ca_street_type,ca_suite_number,ca_city,ca_county,ca_state,ca_zip,ca_country,ca_gmt_offset
                  ,ca_location_type,ctr_total_return
 limit 100
"""

QUERIES["q92"] = r"""
select
   sum(ws_ext_discount_amt)  as `Excess Discount Amount`
from
    web_sales
   ,item
   ,date_dim
where
i_manufact_id = 561
and i_item_sk = ws_item_sk
and d_date between '2001-03-13' and
        (cast('2001-03-13' as date) + INTERVAL 90 days)
and d_date_sk = ws_sold_date_sk
and ws_ext_discount_amt
     > (
         SELECT
            1.3 * avg(ws_ext_discount_amt)
         FROM
            web_sales
           ,date_dim
         WHERE
              ws_item_sk = i_item_sk
          and d_date between '2001-03-13' and
                             (cast('2001-03-13' as date) + INTERVAL 90 days)
          and d_date_sk = ws_sold_date_sk
      )
order by sum(ws_ext_discount_amt)
limit 100
"""

# --- added in round 4 (third wave): GROUP BY ROLLUP + grouping() (verbatim) ---

QUERIES["q5"] = r"""
with ssr as
 (select s_store_id,
        sum(sales_price) as sales,
        sum(profit) as profit,
        sum(return_amt) as returns,
        sum(net_loss) as profit_loss
 from
  ( select  ss_store_sk as store_sk,
            ss_sold_date_sk  as date_sk,
            ss_ext_sales_price as sales_price,
            ss_net_profit as profit,
            cast(0 as decimal(7,2)) as return_amt,
            cast(0 as decimal(7,2)) as net_loss
    from store_sales
    union all
    select sr_store_sk as store_sk,
           sr_returned_date_sk as date_sk,
           cast(0 as decimal(7,2)) as sales_price,
           cast(0 as decimal(7,2)) as profit,
           sr_return_amt as return_amt,
           sr_net_loss as net_loss
    from store_returns
   ) salesreturns,
     date_dim,
     store
 where date_sk = d_date_sk
       and d_date between cast('2000-08-19' as date)
                  and (cast('2000-08-19' as date) +  INTERVAL 14 days)
       and store_sk = s_store_sk
 group by s_store_id)
 ,
 csr as
 (select cp_catalog_page_id,
        sum(sales_price) as sales,
        sum(profit) as profit,
        sum(return_amt) as returns,
        sum(net_loss) as profit_loss
 from
  ( select  cs_catalog_page_sk as page_sk,
            cs_sold_date_sk  as date_sk,
            cs_ext_sales_price as sales_price,
            cs_net_profit as profit,
            cast(0 as decimal(7,2)) as return_amt,
            cast(0 as decimal(7,2)) as net_loss
    from catalog_sales
    union all
    select cr_catalog_page_sk as page_sk,
           cr_returned_date_sk as date_sk,
           cast(0 as decimal(7,2)) as sales_price,
           cast(0 as decimal(7,2)) as profit,
           cr_return_amount as return_amt,
           cr_net_loss as net_loss
    from catalog_returns
   ) salesreturns,
     date_dim,
     catalog_page
 where date_sk = d_date_sk
       and d_date between cast('2000-08-19' as date)
                  and (cast('2000-08-19' as date) +  INTERVAL 14 days)
       and page_sk = cp_catalog_page_sk
 group by cp_catalog_page_id)
 ,
 wsr as
 (select web_site_id,
        sum(sales_price) as sales,
        sum(profit) as profit,
        sum(return_amt) as returns,
        sum(net_loss) as profit_loss
 from
  ( select  ws_web_site_sk as wsr_web_site_sk,
            ws_sold_date_sk  as date_sk,
            ws_ext_sales_price as sales_price,
            ws_net_profit as profit,
            cast(0 as decimal(7,2)) as return_amt,
            cast(0 as decimal(7,2)) as net_loss
    from web_sales
    union all
    select ws_web_site_sk as wsr_web_site_sk,
           wr_returned_date_sk as date_sk,
           cast(0 as decimal(7,2)) as sales_price,
           cast(0 as decimal(7,2)) as profit,
           wr_return_amt as return_amt,
           wr_net_loss as net_loss
    from web_returns left outer join web_sales on
         ( wr_item_sk = ws_item_sk
           and wr_order_number = ws_order_number)
   ) salesreturns,
     date_dim,
     web_site
 where date_sk = d_date_sk
       and d_date between cast('2000-08-19' as date)
                  and (cast('2000-08-19' as date) +  INTERVAL 14 days)
       and wsr_web_site_sk = web_site_sk
 group by web_site_id)
  select  channel
        , id
        , sum(sales) as sales
        , sum(returns) as returns
        , sum(profit) as profit
 from
 (select 'store channel' as channel
        , 'store' || s_store_id as id
        , sales
        , returns
        , (profit - profit_loss) as profit
 from   ssr
 union all
 select 'catalog channel' as channel
        , 'catalog_page' || cp_catalog_page_id as id
        , sales
        , returns
        , (profit - profit_loss) as profit
 from  csr
 union all
 select 'web channel' as channel
        , 'web_site' || web_site_id as id
        , sales
        , returns
        , (profit - profit_loss) as profit
 from   wsr
 ) x
 group by rollup (channel, id)
 order by channel
         ,id
 limit 100
"""

QUERIES["q18"] = r"""
select  i_item_id,
        ca_country,
        ca_state,
        ca_county,
        avg( cast(cs_quantity as decimal(12,2))) agg1,
        avg( cast(cs_list_price as decimal(12,2))) agg2,
        avg( cast(cs_coupon_amt as decimal(12,2))) agg3,
        avg( cast(cs_sales_price as decimal(12,2))) agg4,
        avg( cast(cs_net_profit as decimal(12,2))) agg5,
        avg( cast(c_birth_year as decimal(12,2))) agg6,
        avg( cast(cd1.cd_dep_count as decimal(12,2))) agg7
 from catalog_sales, customer_demographics cd1,
      customer_demographics cd2, customer, customer_address, date_dim, item
 where cs_sold_date_sk = d_date_sk and
       cs_item_sk = i_item_sk and
       cs_bill_cdemo_sk = cd1.cd_demo_sk and
       cs_bill_customer_sk = c_customer_sk and
       cd1.cd_gender = 'F' and
       cd1.cd_education_status = 'Primary' and
       c_current_cdemo_sk = cd2.cd_demo_sk and
       c_current_addr_sk = ca_address_sk and
       c_birth_month in (6,7,3,11,12,8) and
       d_year = 1999 and
       ca_state in ('IL','WV','KS'
                   ,'GA','LA','PA','TX')
 group by rollup (i_item_id, ca_country, ca_state, ca_county)
 order by ca_country,
        ca_state,
        ca_county,
	i_item_id
 limit 100
"""

QUERIES["q27"] = r"""
select  i_item_id,
        s_state, grouping(s_state) g_state,
        avg(ss_quantity) agg1,
        avg(ss_list_price) agg2,
        avg(ss_coupon_amt) agg3,
        avg(ss_sales_price) agg4
 from store_sales, customer_demographics, date_dim, store, item
 where ss_sold_date_sk = d_date_sk and
       ss_item_sk = i_item_sk and
       ss_store_sk = s_store_sk and
       ss_cdemo_sk = cd_demo_sk and
       cd_gender = 'F' and
       cd_marital_status = 'S' and
       cd_education_status = 'Advanced Degree' and
       d_year = 2000 and
       s_state in ('WA','LA', 'LA', 'TX', 'AL', 'PA')
 group by rollup (i_item_id, s_state)
 order by i_item_id
         ,s_state
 limit 100
"""

QUERIES["q36"] = r"""
select
    sum(ss_net_profit)/sum(ss_ext_sales_price) as gross_margin
   ,i_category
   ,i_class
   ,grouping(i_category)+grouping(i_class) as lochierarchy
   ,rank() over (
 	partition by grouping(i_category)+grouping(i_class),
 	case when grouping(i_class) = 0 then i_category end
 	order by sum(ss_net_profit)/sum(ss_ext_sales_price) asc) as rank_within_parent
 from
    store_sales
   ,date_dim       d1
   ,item
   ,store
 where
    d1.d_year = 1998
 and d1.d_date_sk = ss_sold_date_sk
 and i_item_sk  = ss_item_sk
 and s_store_sk  = ss_store_sk
 and s_state in ('OH','WV','PA','TN',
                 'MN','MO','NM','MI')
 group by rollup(i_category,i_class)
 order by
   lochierarchy desc
  ,case when lochierarchy = 0 then i_category end
  ,rank_within_parent
  limit 100
"""

QUERIES["q70"] = r"""
select
    sum(ss_net_profit) as total_sum
   ,s_state
   ,s_county
   ,grouping(s_state)+grouping(s_county) as lochierarchy
   ,rank() over (
 	partition by grouping(s_state)+grouping(s_county),
 	case when grouping(s_county) = 0 then s_state end
 	order by sum(ss_net_profit) desc) as rank_within_parent
 from
    store_sales
   ,date_dim       d1
   ,store
 where
    d1.d_month_seq between 1197 and 1197+11
 and d1.d_date_sk = ss_sold_date_sk
 and s_store_sk  = ss_store_sk
 and s_state in
             ( select s_state
               from  (select s_state as s_state,
 			    rank() over ( partition by s_state order by sum(ss_net_profit) desc) as ranking
                      from   store_sales, store, date_dim
                      where  d_month_seq between 1197 and 1197+11
 			    and d_date_sk = ss_sold_date_sk
 			    and s_store_sk  = ss_store_sk
                      group by s_state
                     ) tmp1
               where ranking <= 5
             )
 group by rollup(s_state,s_county)
 order by
   lochierarchy desc
  ,case when lochierarchy = 0 then s_state end
  ,rank_within_parent
 limit 100
"""

QUERIES["q77"] = r"""
with ss as
 (select s_store_sk,
         sum(ss_ext_sales_price) as sales,
         sum(ss_net_profit) as profit
 from store_sales,
      date_dim,
      store
 where ss_sold_date_sk = d_date_sk
       and d_date between cast('2001-08-27' as date)
                  and (cast('2001-08-27' as date) +  INTERVAL 30 days)
       and ss_store_sk = s_store_sk
 group by s_store_sk)
 ,
 sr as
 (select s_store_sk,
         sum(sr_return_amt) as returns,
         sum(sr_net_loss) as profit_loss
 from store_returns,
      date_dim,
      store
 where sr_returned_date_sk = d_date_sk
       and d_date between cast('2001-08-27' as date)
                  and (cast('2001-08-27' as date) +  INTERVAL 30 days)
       and sr_store_sk = s_store_sk
 group by s_store_sk),
 cs as
 (select cs_call_center_sk,
        sum(cs_ext_sales_price) as sales,
        sum(cs_net_profit) as profit
 from catalog_sales,
      date_dim
 where cs_sold_date_sk = d_date_sk
       and d_date between cast('2001-08-27' as date)
                  and (cast('2001-08-27' as date) +  INTERVAL 30 days)
 group by cs_call_center_sk
 ),
 cr as
 (select cr_call_center_sk,
         sum(cr_return_amount) as returns,
         sum(cr_net_loss) as profit_loss
 from catalog_returns,
      date_dim
 where cr_returned_date_sk = d_date_sk
       and d_date between cast('2001-08-27' as date)
                  and (cast('2001-08-27' as date) +  INTERVAL 30 days)
 group by cr_call_center_sk
 ),
 ws as
 ( select wp_web_page_sk,
        sum(ws_ext_sales_price) as sales,
        sum(ws_net_profit) as profit
 from web_sales,
      date_dim,
      web_page
 where ws_sold_date_sk = d_date_sk
       and d_date between cast('2001-08-27' as date)
                  and (cast('2001-08-27' as date) +  INTERVAL 30 days)
       and ws_web_page_sk = wp_web_page_sk
 group by wp_web_page_sk),
 wr as
 (select wp_web_page_sk,
        sum(wr_return_amt) as returns,
        sum(wr_net_loss) as profit_loss
 from web_returns,
      date_dim,
      web_page
 where wr_returned_date_sk = d_date_sk
       and d_date between cast('2001-08-27' as date)
                  and (cast('2001-08-27' as date) +  INTERVAL 30 days)
       and wr_web_page_sk = wp_web_page_sk
 group by wp_web_page_sk)
  select  channel
        , id
        , sum(sales) as sales
        , sum(returns) as returns
        , sum(profit) as profit
 from
 (select 'store channel' as channel
        , ss.s_store_sk as id
        , sales
        , coalesce(returns, 0) as returns
        , (profit - coalesce(profit_loss,0)) as profit
 from   ss left join sr
        on  ss.s_store_sk = sr.s_store_sk
 union all
 select 'catalog channel' as channel
        , cs_call_center_sk as id
        , sales
        , returns
        , (profit - profit_loss) as profit
 from  cs
       , cr
 union all
 select 'web channel' as channel
        , ws.wp_web_page_sk as id
        , sales
        , coalesce(returns, 0) returns
        , (profit - coalesce(profit_loss,0)) as profit
 from   ws left join wr
        on  ws.wp_web_page_sk = wr.wp_web_page_sk
 ) x
 group by rollup (channel, id)
 order by channel
         ,id
 limit 100
"""

QUERIES["q80"] = r"""
with ssr as
 (select  s_store_id as store_id,
          sum(ss_ext_sales_price) as sales,
          sum(coalesce(sr_return_amt, 0)) as returns,
          sum(ss_net_profit - coalesce(sr_net_loss, 0)) as profit
  from store_sales left outer join store_returns on
         (ss_item_sk = sr_item_sk and ss_ticket_number = sr_ticket_number),
     date_dim,
     store,
     item,
     promotion
 where ss_sold_date_sk = d_date_sk
       and d_date between cast('1999-08-12' as date)
                  and (cast('1999-08-12' as date) +  INTERVAL 60 days)
       and ss_store_sk = s_store_sk
       and ss_item_sk = i_item_sk
       and i_current_price > 50
       and ss_promo_sk = p_promo_sk
       and p_channel_tv = 'N'
 group by s_store_id)
 ,
 csr as
 (select  cp_catalog_page_id as catalog_page_id,
          sum(cs_ext_sales_price) as sales,
          sum(coalesce(cr_return_amount, 0)) as returns,
          sum(cs_net_profit - coalesce(cr_net_loss, 0)) as profit
  from catalog_sales left outer join catalog_returns on
         (cs_item_sk = cr_item_sk and cs_order_number = cr_order_number),
     date_dim,
     catalog_page,
     item,
     promotion
 where cs_sold_date_sk = d_date_sk
       and d_date between cast('1999-08-12' as date)
                  and (cast('1999-08-12' as date) +  INTERVAL 60 days)
        and cs_catalog_page_sk = cp_catalog_page_sk
       and cs_item_sk = i_item_sk
       and i_current_price > 50
       and cs_promo_sk = p_promo_sk
       and p_channel_tv = 'N'
group by cp_catalog_page_id)
 ,
 wsr as
 (select  web_site_id,
          sum(ws_ext_sales_price) as sales,
          sum(coalesce(wr_return_amt, 0)) as returns,
          sum(ws_net_profit - coalesce(wr_net_loss, 0)) as profit
  from web_sales left outer join web_returns on
         (ws_item_sk = wr_item_sk and ws_order_number = wr_order_number),
     date_dim,
     web_site,
     item,
     promotion
 where ws_sold_date_sk = d_date_sk
       and d_date between cast('1999-08-12' as date)
                  and (cast('1999-08-12' as date) +  INTERVAL 60 days)
        and ws_web_site_sk = web_site_sk
       and ws_item_sk = i_item_sk
       and i_current_price > 50
       and ws_promo_sk = p_promo_sk
       and p_channel_tv = 'N'
group by web_site_id)
  select  channel
        , id
        , sum(sales) as sales
        , sum(returns) as returns
        , sum(profit) as profit
 from
 (select 'store channel' as channel
        , 'store' || store_id as id
        , sales
        , returns
        , profit
 from   ssr
 union all
 select 'catalog channel' as channel
        , 'catalog_page' || catalog_page_id as id
        , sales
        , returns
        , profit
 from  csr
 union all
 select 'web channel' as channel
        , 'web_site' || web_site_id as id
        , sales
        , returns
        , profit
 from   wsr
 ) x
 group by rollup (channel, id)
 order by channel
         ,id
 limit 100
"""

QUERIES["q86"] = r"""
select
    sum(ws_net_paid) as total_sum
   ,i_category
   ,i_class
   ,grouping(i_category)+grouping(i_class) as lochierarchy
   ,rank() over (
 	partition by grouping(i_category)+grouping(i_class),
 	case when grouping(i_class) = 0 then i_category end
 	order by sum(ws_net_paid) desc) as rank_within_parent
 from
    web_sales
   ,date_dim       d1
   ,item
 where
    d1.d_month_seq between 1180 and 1180+11
 and d1.d_date_sk = ws_sold_date_sk
 and i_item_sk  = ws_item_sk
 group by rollup(i_category,i_class)
 order by
   lochierarchy desc,
   case when lochierarchy = 0 then i_category end,
   rank_within_parent
 limit 100
"""

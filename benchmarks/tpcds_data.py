"""Seeded TPC-DS-schema data generator.

Generates the 19 TPC-DS tables touched by the verbatim query corpus
(`benchmarks/tpcds_queries.py`) as Arrow tables, with the column names
and types of the TPC-DS v2.4 schema subset those queries reference.
The reference loads dsdgen output (`benchmarks/src/main/scala/
benchmark/TPCDSDataLoad.scala:71`); dsdgen is not redistributable, so
this module plays its role with a seeded numpy generator whose value
distributions are chosen so that **every filter constant in the query
corpus matches rows** (e.g. `i_manufact_id = 816`, `d_moy = 11`,
`cd_education_status = 'College'`, `s_store_name = 'ese'`,
`d_month_seq between 1194 and 1205`).

`scale` = number of store_sales rows; every other table is sized
proportionally. Same seed + scale → identical data, so oracle results
are reproducible.

Facts contain NULLs (~2% of measure values, some nullable FKs) —
TPC-DS data has them, and they exercise SQL null semantics in joins
and aggregates.
"""

from __future__ import annotations

import datetime

import numpy as np
import pyarrow as pa

__all__ = ["generate", "load_delta", "TABLE_NAMES"]

_CATEGORIES = ["Books", "Home", "Electronics", "Jewelry", "Men",
               "Women", "Music", "Shoes", "Sports", "Children"]
# includes every i_class constant in the corpus (q53/q63/q89)
_CLASSES = ["accent", "bedding", "classical", "dresses", "football",
            "infants", "pants", "portable", "romance", "shirts",
            "personal", "reference", "self-help", "accessories",
            "fragrances", "pop", "home repair", "sports-apparel"]
# q53/q63 brand IN-lists; generic Brand#N fills the rest
_BRAND_POOL = ["scholaramalgamalg #14", "scholaramalgamalg #7",
               "exportiunivamalg #9", "scholaramalgamalg #9",
               "amalgimporto #1", "edu packscholar #1",
               "exportiimporto #1", "importoamalg #1"]
_EDUCATION = ["Primary", "Secondary", "College", "2 yr Degree",
              "4 yr Degree", "Advanced Degree", "Unknown"]
_MARITAL = ["M", "S", "D", "W", "U"]
_BUY_POTENTIAL = [">10000", "5001-10000", "1001-5000", "501-1000",
                  "0-500", "Unknown"]
_STORE_NAMES = ["ese", "ought", "able", "bar", "anti", "cally"]
_SM_TYPES = ["EXPRESS", "OVERNIGHT", "REGULAR", "TWO DAY", "LIBRARY"]
_STATES = ["CA", "WA", "GA", "TX", "NY", "FL", "OH", "MI", "IL", "VA",
           "TN", "NE", "IA", "IN", "KY", "AL", "MN", "SD"]
_COUNTIES = ["Williamson County", "Ziebach County", "Walker County",
             "Daviess County", "Fairfield County", "Barrow County",
             "Franklin Parish", "Luce County", "Mobile County"]
_CITIES = ["Midway", "Fairview", "Oakland", "Pleasant Hill", "Centerville",
           "Five Points", "Liberty", "Bethel", "Summit"]
_DAY_NAMES = ["Sunday", "Monday", "Tuesday", "Wednesday", "Thursday",
              "Friday", "Saturday"]
# pools guarantee the corpus' IN-list / equality constants exist
_MANUFACT_POOL = [816, 928, 715, 942, 861, 941, 920, 105, 693]

TABLE_NAMES = [
    "date_dim", "time_dim", "item", "customer", "customer_address",
    "customer_demographics", "household_demographics", "promotion",
    "store", "warehouse", "ship_mode", "web_site", "web_page",
    "catalog_page", "call_center", "reason", "income_band",
    "store_sales", "store_returns", "catalog_sales",
    "catalog_returns", "web_sales", "web_returns", "inventory",
]

_BASE_DATE = datetime.date(1998, 1, 1)
_N_DAYS = 5 * 366  # 1998-01-01 .. 2002-12-31 and a bit

_DATE_SK0 = 2450000  # julian-ish offset like dsdgen's


def _money(rng, n, lo=1.0, hi=300.0, null_frac=0.02):
    v = np.round(rng.uniform(lo, hi, n), 2)
    if null_frac:
        v[rng.random(n) < null_frac] = np.nan
    return pa.array(v)


def _maybe_null_int(rng, vals, null_frac=0.02):
    mask = rng.random(len(vals)) < null_frac
    return pa.array(np.where(mask, None, vals), type=pa.int64(),
                    from_pandas=True) if mask.any() else \
        pa.array(vals.astype(np.int64))


def _date_dim() -> pa.Table:
    days = np.arange(_N_DAYS)
    dates = [_BASE_DATE + datetime.timedelta(days=int(i)) for i in days]
    years = np.array([d.year for d in dates], dtype=np.int64)
    months = np.array([d.month for d in dates], dtype=np.int64)
    return pa.table({
        "d_date_sk": pa.array(_DATE_SK0 + days),
        "d_date": pa.array(dates, type=pa.date32()),
        "d_year": pa.array(years),
        "d_moy": pa.array(months),
        "d_dom": pa.array(np.array([d.day for d in dates], np.int64)),
        "d_qoy": pa.array((months - 1) // 3 + 1),
        # (year-1900)*12 + month-1: 1998-07=1182 .. 2002-09=1232 covers
        # every d_month_seq window in the corpus (1186..1232)
        "d_month_seq": pa.array((years - 1900) * 12 + months - 1),
        "d_week_seq": pa.array((days // 7) + 5100),
        "d_quarter_name": pa.array(
            [f"{d.year}Q{(d.month - 1) // 3 + 1}" for d in dates]),
        "d_day_name": pa.array(
            [_DAY_NAMES[d.weekday() if d.weekday() != 6 else 6]
             for d in dates]),
        "d_dow": pa.array(np.array(
            [(d.weekday() + 1) % 7 for d in dates], np.int64)),
    })


def _time_dim() -> pa.Table:
    mins = np.arange(24 * 60)
    hours = mins // 60
    meal = np.where(hours < 9, "breakfast",
                    np.where((hours >= 11) & (hours < 14), "lunch",
                             np.where((hours >= 17) & (hours < 21),
                                      "dinner", None)))
    return pa.table({
        "t_time_sk": pa.array(mins * 60),  # sk = second of day
        "t_time": pa.array(mins * 60),
        "t_hour": pa.array(hours),
        "t_minute": pa.array(mins % 60),
        "t_meal_time": pa.array(meal.tolist()),
    })


def _item(rng, n_items) -> pa.Table:
    sk = np.arange(1, n_items + 1)
    manufact = np.where(
        rng.random(n_items) < 0.3,
        rng.choice(_MANUFACT_POOL, n_items),
        rng.integers(1, 1000, n_items))
    brand_id = rng.integers(1, 500, n_items)
    cat_id = rng.integers(1, len(_CATEGORIES) + 1, n_items)
    return pa.table({
        "i_item_sk": pa.array(sk),
        "i_item_id": pa.array([f"AAAAAAAA{j:08d}" for j in sk]),
        "i_item_desc": pa.array([f"item description {j % 97}"
                                 for j in sk]),
        "i_brand_id": pa.array(brand_id),
        "i_brand": pa.array(
            [_BRAND_POOL[j % len(_BRAND_POOL)] if j % 4 == 0
             else f"Brand#{b}"
             for j, b in zip(sk, brand_id)]),
        "i_class_id": pa.array(rng.integers(1, 11, n_items)),
        "i_class": pa.array([_CLASSES[c] for c in
                             rng.integers(0, len(_CLASSES), n_items)]),
        "i_category_id": pa.array(cat_id),
        "i_category": pa.array([_CATEGORIES[c - 1] for c in cat_id]),
        "i_manufact_id": pa.array(manufact.astype(np.int64)),
        "i_manufact": pa.array([f"manufact#{m}" for m in manufact]),
        # deterministic cycle: every manager id 1..100 owns items, so
        # the corpus' i_manager_id = 1/26/87 filters always match
        "i_manager_id": pa.array((sk - 1) % 100 + 1),
        "i_current_price": pa.array(
            np.round(rng.uniform(1.0, 120.0, n_items), 2)),
        "i_wholesale_cost": pa.array(
            np.round(rng.uniform(1.0, 80.0, n_items), 2)),
        "i_product_name": pa.array(
            [f"product {j % 211}ought" for j in sk]),
        "i_color": pa.array(
            [["slate", "blanched", "burnished", "peach", "metallic",
              "dim", "red", "navy"][c]
             for c in rng.integers(0, 8, n_items)]),
        "i_size": pa.array(
            [["small", "medium", "large", "petite", "extra large",
              "economy", "N/A"][c] for c in rng.integers(0, 7, n_items)]),
        "i_units": pa.array(
            [["Each", "Dozen", "Case", "Pallet", "Oz", "Lb"][c]
             for c in rng.integers(0, 6, n_items)]),
    })


def _customer(rng, n_cust, n_addr) -> pa.Table:
    sk = np.arange(1, n_cust + 1)
    first = ["James", "Mary", "John", "Linda", "Robert", "Ann",
             "Michael", "Susan"]
    last = ["Smith", "Jones", "Brown", "Lee", "Garcia", "Miller",
            "Davis", "Moore"]
    return pa.table({
        "c_customer_sk": pa.array(sk),
        "c_customer_id": pa.array([f"CUST{j:012d}" for j in sk]),
        "c_current_addr_sk": pa.array(
            rng.integers(1, n_addr + 1, n_cust)),
        "c_current_cdemo_sk": pa.array(rng.integers(1, 71, n_cust)),
        "c_current_hdemo_sk": pa.array(rng.integers(1, 301, n_cust)),
        "c_first_name": pa.array(
            [first[i] for i in rng.integers(0, len(first), n_cust)]),
        "c_last_name": pa.array(
            [last[i] for i in rng.integers(0, len(last), n_cust)]),
        "c_salutation": pa.array(
            [["Mr.", "Ms.", "Dr."][i]
             for i in rng.integers(0, 3, n_cust)]),
        "c_preferred_cust_flag": pa.array(
            [["Y", "N"][i] for i in rng.integers(0, 2, n_cust)]),
        "c_birth_country": pa.array(
            [["UNITED STATES", "CANADA", "MEXICO"][i]
             for i in rng.integers(0, 3, n_cust)]),
        "c_birth_day": pa.array(
            rng.integers(1, 29, n_cust).astype(np.int64)),
        "c_birth_month": pa.array(
            rng.integers(1, 13, n_cust).astype(np.int64)),
        "c_birth_year": pa.array(
            rng.integers(1930, 1995, n_cust).astype(np.int64)),
        "c_login": pa.array([f"user{j}" for j in sk]),
        "c_email_address": pa.array(
            [f"user{j}@example.com" for j in sk]),
        "c_last_review_date": pa.array(
            (_DATE_SK0 + rng.integers(0, _N_DAYS, n_cust)).astype(
                np.int64)),
        "c_first_sales_date_sk": pa.array(
            (_DATE_SK0 + rng.integers(0, _N_DAYS, n_cust)).astype(
                np.int64)),
        "c_first_shipto_date_sk": pa.array(
            (_DATE_SK0 + rng.integers(0, _N_DAYS, n_cust)).astype(
                np.int64)),
    })


def _customer_address(rng, n_addr) -> pa.Table:
    sk = np.arange(1, n_addr + 1)
    zips = np.where(rng.random(n_addr) < 0.1,
                    rng.choice([85669, 86197, 88274, 83405, 86475,
                                85392, 85460, 80348, 81792], n_addr),
                    rng.integers(10000, 99999, n_addr))
    return pa.table({
        "ca_address_sk": pa.array(sk),
        "ca_zip": pa.array([f"{z:05d}" for z in zips]),
        "ca_state": pa.array(
            [_STATES[i] for i in rng.integers(0, len(_STATES), n_addr)]),
        "ca_city": pa.array(
            [_CITIES[i] for i in rng.integers(0, len(_CITIES), n_addr)]),
        "ca_county": pa.array(
            [_COUNTIES[i]
             for i in rng.integers(0, len(_COUNTIES), n_addr)]),
        "ca_country": pa.array(["United States"] * n_addr),
        "ca_street_number": pa.array(
            [str(z) for z in rng.integers(1, 1000, n_addr)]),
        "ca_street_name": pa.array(
            [["Main", "Oak", "Park", "First"][i]
             for i in rng.integers(0, 4, n_addr)]),
        "ca_street_type": pa.array(
            [["St", "Ave", "Blvd", "Ln"][i]
             for i in rng.integers(0, 4, n_addr)]),
        "ca_suite_number": pa.array(
            [f"Suite {z}" for z in rng.integers(0, 500, n_addr)]),
        "ca_gmt_offset": pa.array(
            np.where(rng.random(n_addr) < 0.5, -6.0, -5.0)),
        "ca_location_type": pa.array(
            [["apartment", "condo", "single family"][i]
             for i in rng.integers(0, 3, n_addr)]),
    })


def _customer_demographics() -> pa.Table:
    rows = [(g, m, e)
            for g in ("M", "F")
            for m in _MARITAL
            for e in _EDUCATION]
    return pa.table({
        "cd_demo_sk": pa.array(np.arange(1, len(rows) + 1)),
        "cd_gender": pa.array([r[0] for r in rows]),
        "cd_marital_status": pa.array([r[1] for r in rows]),
        "cd_education_status": pa.array([r[2] for r in rows]),
        "cd_dep_count": pa.array(
            np.arange(len(rows), dtype=np.int64) % 7),
        "cd_dep_employed_count": pa.array(
            np.arange(len(rows), dtype=np.int64) % 5),
        "cd_dep_college_count": pa.array(
            np.arange(len(rows), dtype=np.int64) % 4),
        "cd_purchase_estimate": pa.array(
            (np.arange(len(rows), dtype=np.int64) % 12) * 500 + 500),
        "cd_credit_rating": pa.array(
            [["Low Risk", "High Risk", "Good", "Unknown"][j % 4]
             for j in range(len(rows))]),
    })


def _household_demographics() -> pa.Table:
    rows = [(d, v, b)
            for d in range(10)
            for v in range(5)
            for b in _BUY_POTENTIAL]
    return pa.table({
        "hd_demo_sk": pa.array(np.arange(1, len(rows) + 1)),
        "hd_dep_count": pa.array(np.array([r[0] for r in rows],
                                          np.int64)),
        "hd_vehicle_count": pa.array(np.array([r[1] for r in rows],
                                              np.int64)),
        "hd_buy_potential": pa.array([r[2] for r in rows]),
        "hd_income_band_sk": pa.array(
            np.arange(len(rows), dtype=np.int64) % 20 + 1),
    })


def _promotion(rng) -> pa.Table:
    n = 30
    return pa.table({
        "p_promo_sk": pa.array(np.arange(1, n + 1)),
        "p_channel_email": pa.array(
            [["N", "Y"][i] for i in rng.integers(0, 2, n)]),
        "p_channel_event": pa.array(
            [["N", "Y"][i] for i in rng.integers(0, 2, n)]),
        "p_channel_dmail": pa.array(
            [["N", "Y"][i] for i in rng.integers(0, 2, n)]),
        "p_channel_tv": pa.array(
            [["N", "Y"][i] for i in rng.integers(0, 2, n)]),
    })


def _store(rng) -> pa.Table:
    n = 12
    sk = np.arange(1, n + 1)
    return pa.table({
        "s_store_sk": pa.array(sk),
        "s_store_id": pa.array([f"STORE{j:010d}" for j in sk]),
        "s_store_name": pa.array(
            [_STORE_NAMES[j % len(_STORE_NAMES)] for j in sk]),
        "s_gmt_offset": pa.array(
            np.where(sk % 2 == 0, -6.0, -5.0)),
        "s_zip": pa.array([f"{z:05d}" for z in
                           rng.integers(10000, 99999, n)]),
        "s_city": pa.array(
            [_CITIES[i] for i in rng.integers(0, len(_CITIES), n)]),
        "s_county": pa.array(
            [_COUNTIES[i] for i in rng.integers(0, len(_COUNTIES), n)]),
        "s_state": pa.array(
            [_STATES[j % len(_STATES)] for j in range(n)]),
        "s_number_employees": pa.array(
            rng.integers(200, 301, n).astype(np.int64)),
        "s_market_id": pa.array(
            rng.integers(1, 11, n).astype(np.int64)),
        "s_company_id": pa.array(np.ones(n, np.int64)),
        "s_company_name": pa.array(["Unknown"] * n),
        "s_street_number": pa.array(
            [str(z) for z in rng.integers(1, 1000, n)]),
        "s_street_name": pa.array(
            [["Main", "Oak", "Park", "First"][i]
             for i in rng.integers(0, 4, n)]),
        "s_street_type": pa.array(
            [["St", "Ave", "Blvd", "Ln"][i]
             for i in rng.integers(0, 4, n)]),
        "s_suite_number": pa.array(
            [f"Suite {z}" for z in rng.integers(0, 500, n)]),
    })


def _warehouse(rng) -> pa.Table:
    n = 5
    return pa.table({
        "w_warehouse_sk": pa.array(np.arange(1, n + 1)),
        "w_warehouse_name": pa.array(
            [f"Warehouse number {j} of the chain" for j in range(n)]),
        "w_warehouse_sq_ft": pa.array(
            rng.integers(50_000, 1_000_000, n).astype(np.int64)),
        "w_city": pa.array(
            [_CITIES[i] for i in rng.integers(0, len(_CITIES), n)]),
        "w_county": pa.array(
            [_COUNTIES[i] for i in rng.integers(0, len(_COUNTIES), n)]),
        "w_state": pa.array(
            [_STATES[i] for i in rng.integers(0, len(_STATES), n)]),
        "w_country": pa.array(["United States"] * n),
    })


def _ship_mode() -> pa.Table:
    n = len(_SM_TYPES) * 4
    return pa.table({
        "sm_ship_mode_sk": pa.array(np.arange(1, n + 1)),
        "sm_type": pa.array([_SM_TYPES[j % len(_SM_TYPES)]
                             for j in range(n)]),
        "sm_carrier": pa.array(
            [["UPS", "FEDEX", "AIRBORNE", "USPS", "DHL"][j % 5]
             for j in range(n)]),
    })


def _web_site() -> pa.Table:
    n = 6
    return pa.table({
        "web_site_sk": pa.array(np.arange(1, n + 1)),
        "web_site_id": pa.array([f"SITE{j:012d}" for j in range(n)]),
        "web_name": pa.array([f"site_{j}" for j in range(n)]),
        "web_company_name": pa.array(["pri"] * n),
    })


def _web_page(rng) -> pa.Table:
    n = 60
    return pa.table({
        "wp_web_page_sk": pa.array(np.arange(1, n + 1)),
        "wp_char_count": pa.array(
            rng.integers(4000, 6000, n).astype(np.int64)),
    })


def _reason() -> pa.Table:
    n = 9
    sk = np.arange(1, n + 1)
    descs = ["Package was damaged", "Stopped working", "Did not get it",
             "Not the product that was ordred", "Parts missing",
             "Does not work with a product that I have",
             "Gift exchange", "Did not like the color",
             "Did not like the model"]
    return pa.table({
        "r_reason_sk": pa.array(sk),
        "r_reason_desc": pa.array(descs),
    })


def _income_band() -> pa.Table:
    n = 20
    sk = np.arange(1, n + 1)
    return pa.table({
        "ib_income_band_sk": pa.array(sk),
        "ib_lower_bound": pa.array((sk - 1) * 10000),
        "ib_upper_bound": pa.array(sk * 10000),
    })


def _catalog_page() -> pa.Table:
    n = 20
    sk = np.arange(1, n + 1)
    return pa.table({
        "cp_catalog_page_sk": pa.array(sk),
        "cp_catalog_page_id": pa.array(
            [f"PAGE{j:012d}" for j in sk]),
    })


def _call_center() -> pa.Table:
    n = 4
    return pa.table({
        "cc_call_center_sk": pa.array(np.arange(1, n + 1)),
        "cc_call_center_id": pa.array(
            [f"CC{j:014d}" for j in range(n)]),
        "cc_name": pa.array([f"call center {j}" for j in range(n)]),
        "cc_manager": pa.array([f"Manager {j}" for j in range(n)]),
        "cc_county": pa.array(
            [_COUNTIES[j % len(_COUNTIES)] for j in range(n)]),
    })


def generate(scale: int = 50_000, seed: int = 7):
    """Return {table_name: pa.Table} for all 19 tables; `scale` =
    store_sales row count."""
    rng = np.random.default_rng(seed)
    n_items = max(200, scale // 250)
    n_cust = max(500, scale // 50)
    n_addr = n_cust

    tables = {
        "date_dim": _date_dim(),
        "time_dim": _time_dim(),
        "item": _item(rng, n_items),
        "customer": _customer(rng, n_cust, n_addr),
        "customer_address": _customer_address(rng, n_addr),
        "customer_demographics": _customer_demographics(),
        "household_demographics": _household_demographics(),
        "promotion": _promotion(rng),
        "store": _store(rng),
        "warehouse": _warehouse(rng),
        "ship_mode": _ship_mode(),
        "web_site": _web_site(),
        "web_page": _web_page(rng),
        "catalog_page": _catalog_page(),
        "call_center": _call_center(),
        "reason": _reason(),
        "income_band": _income_band(),
    }

    n_cd = tables["customer_demographics"].num_rows
    n_hd = tables["household_demographics"].num_rows
    n_store = tables["store"].num_rows
    n_wh = tables["warehouse"].num_rows
    n_sm = tables["ship_mode"].num_rows
    n_ws_site = tables["web_site"].num_rows
    n_wp = tables["web_page"].num_rows
    n_cc = tables["call_center"].num_rows
    time_sks = tables["time_dim"].column("t_time_sk").to_numpy()

    # ---- store_sales --------------------------------------------------
    # ticket-structured: a ticket is one basket — same customer, store,
    # date, time, demographics for all its line items (the reference's
    # dsdgen does the same); ticket sizes 1..25 so the q34/q73
    # `cnt between 15 and 20` shapes have matches
    n = scale
    t_sizes = rng.integers(1, 26, n)
    ticket_of_row = np.repeat(np.arange(n), t_sizes)[:n]
    n_tickets = int(ticket_of_row[-1]) + 1
    t_day = rng.integers(0, _N_DAYS, n_tickets)
    t_time = rng.choice(time_sks, n_tickets).astype(np.int64)
    t_cust = rng.integers(1, n_cust + 1, n_tickets)
    t_cdemo = rng.integers(1, n_cd + 1, n_tickets)
    t_hdemo = rng.integers(1, n_hd + 1, n_tickets)
    t_addr = rng.integers(1, n_addr + 1, n_tickets)
    t_store = rng.integers(1, n_store + 1, n_tickets)
    sold_day = t_day[ticket_of_row]
    qty = rng.integers(1, 101, n).astype(np.int64)
    sales_price = np.round(rng.uniform(1.0, 200.0, n), 2)
    tables["store_sales"] = pa.table({
        "ss_sold_date_sk": _maybe_null_int(rng, _DATE_SK0 + sold_day,
                                           0.01),
        "ss_sold_time_sk": pa.array(t_time[ticket_of_row]),
        "ss_item_sk": pa.array(
            rng.integers(1, n_items + 1, n).astype(np.int64)),
        "ss_customer_sk": pa.array(
            t_cust[ticket_of_row].astype(np.int64)),
        "ss_cdemo_sk": _maybe_null_int(
            rng, t_cdemo[ticket_of_row], 0.03),
        "ss_hdemo_sk": pa.array(
            t_hdemo[ticket_of_row].astype(np.int64)),
        "ss_addr_sk": _maybe_null_int(
            rng, t_addr[ticket_of_row], 0.03),
        "ss_store_sk": pa.array(
            t_store[ticket_of_row].astype(np.int64)),
        "ss_promo_sk": _maybe_null_int(
            rng, rng.integers(1, 31, n), 0.05),
        "ss_ticket_number": pa.array(
            (ticket_of_row + 1).astype(np.int64)),
        "ss_quantity": pa.array(qty),
        "ss_list_price": _money(rng, n, 1, 250),
        "ss_sales_price": pa.array(sales_price),
        "ss_ext_sales_price": _money(rng, n, 1, 2000),
        "ss_ext_discount_amt": _money(rng, n, 0, 100),
        "ss_ext_list_price": _money(rng, n, 1, 2500),
        "ss_ext_wholesale_cost": _money(rng, n, 1, 1500),
        "ss_ext_tax": _money(rng, n, 0, 150),
        "ss_coupon_amt": _money(rng, n, 0, 50),
        "ss_net_paid": _money(rng, n, 1, 2000),
        "ss_net_paid_inc_tax": _money(rng, n, 1, 2100),
        "ss_net_profit": pa.array(
            np.round(rng.uniform(-5000.0, 5000.0, n), 2)),
        "ss_wholesale_cost": _money(rng, n, 1, 100),
    })

    # ---- store_returns (sampled from sales, so the
    # customer+item+ticket join chain of q17/q25/q29/q50 has matches) --
    nr = max(100, scale // 8)
    ret_idx = rng.integers(0, n, nr)
    ss_item = tables["store_sales"].column("ss_item_sk").to_numpy()
    ret_day = np.minimum(sold_day[ret_idx] + rng.integers(1, 100, nr),
                         _N_DAYS - 1)
    tables["store_returns"] = pa.table({
        "sr_returned_date_sk": pa.array(
            (_DATE_SK0 + ret_day).astype(np.int64)),
        "sr_item_sk": pa.array(ss_item[ret_idx]),
        "sr_customer_sk": pa.array(
            t_cust[ticket_of_row[ret_idx]].astype(np.int64)),
        "sr_cdemo_sk": pa.array(
            t_cdemo[ticket_of_row[ret_idx]].astype(np.int64)),
        "sr_store_sk": pa.array(
            t_store[ticket_of_row[ret_idx]].astype(np.int64)),
        "sr_ticket_number": pa.array(
            (ticket_of_row[ret_idx] + 1).astype(np.int64)),
        "sr_reason_sk": pa.array(
            rng.integers(1, 10, nr).astype(np.int64)),
        "sr_return_quantity": pa.array(
            rng.integers(1, 50, nr).astype(np.int64)),
        "sr_return_amt": _money(rng, nr, 1, 500),
        "sr_fee": _money(rng, nr, 1, 100),
        "sr_net_loss": _money(rng, nr, 1, 300),
    })

    # ---- catalog_sales ------------------------------------------------
    nc = max(200, scale // 2)
    c_sold = rng.integers(0, _N_DAYS, nc)
    # ~40% of catalog orders come from customers re-buying a returned
    # item: feeds the sr→cs leg of the q17/q25/q29 triple join
    sr_cust = tables["store_returns"].column(
        "sr_customer_sk").to_numpy()
    sr_item = tables["store_returns"].column("sr_item_sk").to_numpy()
    pick = rng.integers(0, nr, nc)
    reuse = rng.random(nc) < 0.4
    cs_cust = np.where(reuse, sr_cust[pick],
                       rng.integers(1, n_cust + 1, nc))
    cs_item = np.where(reuse, sr_item[pick],
                       rng.integers(1, n_items + 1, nc))
    tables["catalog_sales"] = pa.table({
        "cs_sold_date_sk": _maybe_null_int(rng, _DATE_SK0 + c_sold,
                                           0.01),
        "cs_sold_time_sk": pa.array(
            rng.choice(time_sks, nc).astype(np.int64)),
        "cs_ship_date_sk": pa.array(
            (_DATE_SK0 + np.minimum(c_sold + rng.integers(1, 140, nc),
                                    _N_DAYS - 1)).astype(np.int64)),
        "cs_item_sk": pa.array(cs_item.astype(np.int64)),
        "cs_bill_customer_sk": pa.array(cs_cust.astype(np.int64)),
        "cs_bill_cdemo_sk": pa.array(
            rng.integers(1, n_cd + 1, nc).astype(np.int64)),
        "cs_bill_hdemo_sk": pa.array(
            rng.integers(1, n_hd + 1, nc).astype(np.int64)),
        "cs_bill_addr_sk": pa.array(
            rng.integers(1, n_addr + 1, nc).astype(np.int64)),
        "cs_ship_customer_sk": _maybe_null_int(
            rng, rng.integers(1, n_cust + 1, nc), 0.03),
        "cs_ship_addr_sk": pa.array(
            rng.integers(1, n_addr + 1, nc).astype(np.int64)),
        "cs_ship_mode_sk": pa.array(
            rng.integers(1, n_sm + 1, nc).astype(np.int64)),
        "cs_warehouse_sk": _maybe_null_int(
            rng, rng.integers(1, n_wh + 1, nc), 0.03),
        "cs_call_center_sk": pa.array(
            rng.integers(1, n_cc + 1, nc).astype(np.int64)),
        "cs_promo_sk": _maybe_null_int(
            rng, rng.integers(1, 31, nc), 0.05),
        "cs_catalog_page_sk": _maybe_null_int(
            rng, rng.integers(1, 21, nc), 0.03),
        "cs_order_number": pa.array((np.arange(nc) // 2 + 1)),
        "cs_quantity": pa.array(rng.integers(1, 101, nc).astype(
            np.int64)),
        "cs_list_price": _money(rng, nc, 1, 250),
        "cs_sales_price": _money(rng, nc, 1, 600, null_frac=0.0),
        "cs_ext_sales_price": _money(rng, nc, 1, 2000),
        "cs_coupon_amt": _money(rng, nc, 0, 50),
        "cs_ext_discount_amt": _money(rng, nc, 0, 100),
        "cs_ext_ship_cost": _money(rng, nc, 0, 100),
        "cs_ext_list_price": _money(rng, nc, 1, 2500),
        "cs_ext_wholesale_cost": _money(rng, nc, 1, 1500),
        "cs_net_paid": _money(rng, nc, 1, 2000),
        "cs_net_paid_inc_ship": _money(rng, nc, 1, 2100),
        "cs_net_paid_inc_ship_tax": _money(rng, nc, 1, 2200),
        "cs_wholesale_cost": _money(rng, nc, 1, 100),
        "cs_net_profit": pa.array(
            np.round(rng.uniform(-4000.0, 4000.0, nc), 2)),
    })

    # ---- catalog_returns (sampled from catalog_sales) -----------------
    ncr = max(100, nc // 8)
    cr_idx = rng.integers(0, nc, ncr)
    cs_item_np = tables["catalog_sales"].column("cs_item_sk").to_numpy()
    cs_ono_np = tables["catalog_sales"].column(
        "cs_order_number").to_numpy()
    cr_day = np.minimum(c_sold[cr_idx] + rng.integers(1, 100, ncr),
                        _N_DAYS - 1)
    tables["catalog_returns"] = pa.table({
        "cr_returned_date_sk": pa.array(
            (_DATE_SK0 + cr_day).astype(np.int64)),
        "cr_item_sk": pa.array(cs_item_np[cr_idx]),
        "cr_order_number": pa.array(cs_ono_np[cr_idx]),
        "cr_returning_customer_sk": pa.array(
            cs_cust[cr_idx].astype(np.int64)),
        "cr_returning_addr_sk": pa.array(
            rng.integers(1, n_addr + 1, ncr).astype(np.int64)),
        "cr_call_center_sk": pa.array(
            rng.integers(1, n_cc + 1, ncr).astype(np.int64)),
        "cr_catalog_page_sk": pa.array(
            rng.integers(1, 21, ncr).astype(np.int64)),
        "cr_return_quantity": pa.array(
            rng.integers(1, 50, ncr).astype(np.int64)),
        "cr_return_amount": _money(rng, ncr, 1, 500),
        "cr_return_amt_inc_tax": _money(rng, ncr, 1, 550),
        "cr_refunded_cash": _money(rng, ncr, 0, 400),
        "cr_reversed_charge": _money(rng, ncr, 0, 100),
        "cr_store_credit": _money(rng, ncr, 0, 100),
        "cr_net_loss": _money(rng, ncr, 1, 300),
    })

    # ---- web_sales ----------------------------------------------------
    nw = max(200, scale // 2)
    w_sold = rng.integers(0, _N_DAYS, nw)
    tables["web_sales"] = pa.table({
        "ws_sold_date_sk": _maybe_null_int(rng, _DATE_SK0 + w_sold,
                                           0.01),
        "ws_sold_time_sk": pa.array(
            rng.choice(time_sks, nw).astype(np.int64)),
        "ws_ship_date_sk": pa.array(
            (_DATE_SK0 + np.minimum(w_sold + rng.integers(1, 140, nw),
                                    _N_DAYS - 1)).astype(np.int64)),
        "ws_item_sk": pa.array(
            rng.integers(1, n_items + 1, nw).astype(np.int64)),
        "ws_bill_customer_sk": pa.array(
            rng.integers(1, n_cust + 1, nw).astype(np.int64)),
        "ws_bill_addr_sk": pa.array(
            rng.integers(1, n_addr + 1, nw).astype(np.int64)),
        "ws_ship_customer_sk": pa.array(
            rng.integers(1, n_cust + 1, nw).astype(np.int64)),
        "ws_ship_hdemo_sk": _maybe_null_int(
            rng, rng.integers(1, n_hd + 1, nw), 0.03),
        "ws_ship_addr_sk": pa.array(
            rng.integers(1, n_addr + 1, nw).astype(np.int64)),
        "ws_ship_mode_sk": pa.array(
            rng.integers(1, n_sm + 1, nw).astype(np.int64)),
        "ws_warehouse_sk": pa.array(
            rng.integers(1, n_wh + 1, nw).astype(np.int64)),
        "ws_web_site_sk": pa.array(
            rng.integers(1, n_ws_site + 1, nw).astype(np.int64)),
        "ws_web_page_sk": _maybe_null_int(
            rng, rng.integers(1, n_wp + 1, nw), 0.03),
        "ws_promo_sk": _maybe_null_int(
            rng, rng.integers(1, 31, nw), 0.05),
        "ws_order_number": pa.array((np.arange(nw) // 2 + 1)),
        "ws_quantity": pa.array(rng.integers(1, 101, nw).astype(
            np.int64)),
        "ws_list_price": _money(rng, nw, 1, 250),
        "ws_sales_price": _money(rng, nw, 1, 600, null_frac=0.0),
        "ws_ext_sales_price": _money(rng, nw, 1, 2000),
        "ws_ext_ship_cost": _money(rng, nw, 0, 100),
        "ws_ext_discount_amt": _money(rng, nw, 0, 100),
        "ws_ext_list_price": _money(rng, nw, 1, 2500),
        "ws_ext_wholesale_cost": _money(rng, nw, 1, 1500),
        "ws_wholesale_cost": _money(rng, nw, 1, 100),
        "ws_net_paid": _money(rng, nw, 1, 2000),
        "ws_net_paid_inc_tax": _money(rng, nw, 1, 2100),
        "ws_net_profit": pa.array(
            np.round(rng.uniform(-4000.0, 4000.0, nw), 2)),
    })

    # ---- web_returns (sampled from web_sales) -------------------------
    nwr = max(100, nw // 8)
    wr_idx = rng.integers(0, nw, nwr)
    ws_item_np = tables["web_sales"].column("ws_item_sk").to_numpy()
    ws_ono_np = tables["web_sales"].column("ws_order_number").to_numpy()
    ws_cust_np = tables["web_sales"].column(
        "ws_bill_customer_sk").to_numpy()
    wr_day = np.minimum(w_sold[wr_idx] + rng.integers(1, 100, nwr),
                        _N_DAYS - 1)
    tables["web_returns"] = pa.table({
        "wr_returned_date_sk": pa.array(
            (_DATE_SK0 + wr_day).astype(np.int64)),
        "wr_item_sk": pa.array(ws_item_np[wr_idx]),
        "wr_order_number": pa.array(ws_ono_np[wr_idx]),
        "wr_returning_customer_sk": pa.array(ws_cust_np[wr_idx]),
        "wr_refunded_cdemo_sk": pa.array(
            rng.integers(1, n_cd + 1, nwr).astype(np.int64)),
        "wr_returning_cdemo_sk": pa.array(
            rng.integers(1, n_cd + 1, nwr).astype(np.int64)),
        "wr_refunded_addr_sk": pa.array(
            rng.integers(1, n_addr + 1, nwr).astype(np.int64)),
        "wr_returning_addr_sk": pa.array(
            rng.integers(1, n_addr + 1, nwr).astype(np.int64)),
        "wr_web_page_sk": pa.array(
            rng.integers(1, n_wp + 1, nwr).astype(np.int64)),
        "wr_reason_sk": pa.array(
            rng.integers(1, 10, nwr).astype(np.int64)),
        "wr_return_quantity": pa.array(
            rng.integers(1, 50, nwr).astype(np.int64)),
        "wr_return_amt": _money(rng, nwr, 1, 500),
        "wr_refunded_cash": _money(rng, nwr, 0, 400),
        "wr_fee": _money(rng, nwr, 1, 100),
        "wr_net_loss": _money(rng, nwr, 1, 300),
    })

    # ---- inventory (weekly snapshots) ---------------------------------
    weeks = np.arange(0, _N_DAYS, 7)
    inv_items = np.arange(1, n_items + 1)
    grid_d, grid_i = np.meshgrid(weeks, inv_items, indexing="ij")
    ninv = grid_d.size
    tables["inventory"] = pa.table({
        "inv_date_sk": pa.array(_DATE_SK0 + grid_d.ravel()),
        "inv_item_sk": pa.array(grid_i.ravel().astype(np.int64)),
        "inv_warehouse_sk": pa.array(
            rng.integers(1, n_wh + 1, ninv).astype(np.int64)),
        "inv_quantity_on_hand": pa.array(
            np.clip(rng.lognormal(5.0, 1.4, ninv), 0, 8000).astype(
                np.int64)),
    })

    return tables


def load_delta(root: str, scale: int = 50_000, seed: int = 7,
               engine=None):
    """Generate + write every table as a Delta table under `root`;
    returns a `Catalog` with all names registered."""
    import os

    import delta_tpu.api as dta
    from delta_tpu.catalog import Catalog

    tables = generate(scale, seed)
    cat = Catalog(root, engine=engine)
    for name, tbl in tables.items():
        path = os.path.join(root, name)
        dta.write_table(path, tbl, engine=engine)
        if not cat.exists(name):
            cat.register(name, path)
    return cat

"""Benchmark harness (the reference `benchmarks/` module role:
`Benchmark.scala:96` — named benchmarks, timed queries, JSON report).

Run: `python -m benchmarks.run --benchmark replay --scale small`
Each benchmark yields {name, metric, value, unit, extra} dicts; the
driver prints a JSON report and a human summary.
"""

from __future__ import annotations

import json
import sys
import time
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Callable, Dict, Iterator, List


@dataclass
class QueryResult:
    name: str
    iteration: int
    duration_ms: float
    extra: Dict = field(default_factory=dict)


@dataclass
class BenchmarkReport:
    benchmark: str
    scale: str
    results: List[QueryResult] = field(default_factory=list)
    metrics: List[Dict] = field(default_factory=list)

    def to_json(self) -> str:
        return json.dumps(
            {
                "benchmark": self.benchmark,
                "scale": self.scale,
                "queries": [
                    {
                        "name": r.name,
                        "iteration": r.iteration,
                        "durationMs": round(r.duration_ms, 2),
                        **r.extra,
                    }
                    for r in self.results
                ],
                "metrics": self.metrics,
            },
            indent=2,
        )


class Benchmark:
    name = "base"

    def __init__(self, scale: str = "small", workdir: str = "/tmp/delta_tpu_bench"):
        self.scale = scale
        self.workdir = workdir
        self.report = BenchmarkReport(self.name, scale)

    @contextmanager
    def timed(self, name: str, iteration: int = 0, **extra) -> Iterator[None]:
        t0 = time.perf_counter()
        yield
        dt = (time.perf_counter() - t0) * 1000
        self.report.results.append(QueryResult(name, iteration, dt, extra))
        print(f"  {name}[{iteration}]: {dt:,.1f} ms", file=sys.stderr)

    def metric(self, metric: str, value: float, unit: str, **extra) -> None:
        m = {"metric": metric, "value": value, "unit": unit, **extra}
        self.report.metrics.append(m)
        print(f"  {metric}: {value:,.1f} {unit}", file=sys.stderr)

    def run(self) -> BenchmarkReport:  # pragma: no cover - abstract
        raise NotImplementedError

"""Device-on-merit benchmark + interconnect cost model (VERDICT r3
ask #4).

Measures, on the real attached accelerator:

1. the LINK: H2D/D2H bandwidth at several transfer sizes and the
   dispatch round-trip latency (tiny-op RTT);
2. three workloads device-vs-host, each with the device COMPUTE time
   isolated by timing the jitted kernel on already-resident operands
   (block_until_ready, best of k):
     - replay @ N rows (FA-coded transfer, the product path),
     - blockwise replay @ N rows (resident bitset, streamed blocks),
     - MERGE-style sort join @ N rows;
   the host side is the strongest vectorized numpy formulation of the
   same algorithm (argsort/searchsorted/lexsort), not a Python loop;
3. a transfer/compute cost model: measured wall ≈ bytes/BW + k·RTT +
   t_compute, validated against the measured walls, then re-evaluated
   with PCIe gen4 x16 parameters (BW 16 GB/s[*], RTT 10 µs) to project
   what the same kernels do on a directly-attached device.

[*] a deliberately conservative effective PCIe figure; real pinned-
memory transfers reach ~20+ GB/s.

Output: one JSON document (default `DEVICE_MERIT.json` at the repo
root) with the raw measurements, the model fit, the per-workload
verdicts, and the projections — the checked-in artifact the round-3
verdict asked for. Run SOLO: background CPU work corrupts the host
baselines.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np

PCIE_BW_BYTES_S = 16e9
PCIE_RTT_S = 10e-6


def _best(fn, k=3):
    out = []
    for _ in range(k):
        t0 = time.perf_counter()
        fn()
        out.append(time.perf_counter() - t0)
    return min(out)


# ------------------------------------------------------------- link --


def measure_link(device):
    import jax
    import jax.numpy as jnp

    sizes = [8 << 20, 64 << 20]
    h2d, d2h = {}, {}
    for size in sizes:
        buf = np.random.default_rng(0).integers(
            0, 255, size, dtype=np.uint8)
        t = _best(lambda: jax.device_put(buf, device).block_until_ready())
        h2d[size] = size / t

        def pull():
            # fresh device array per rep: jax caches np.asarray results
            dbuf = jax.device_put(buf, device)
            dbuf.block_until_ready()
            t0 = time.perf_counter()
            np.asarray(dbuf)
            return time.perf_counter() - t0

        t = min(pull() for _ in range(3))
        d2h[size] = size / t
    one = jax.device_put(np.zeros(8, np.float32), device)
    inc = jax.jit(lambda x: x + 1)
    inc(one).block_until_ready()  # compile
    rtt = _best(lambda: inc(one).block_until_ready(), k=5)
    return {
        "h2d_bytes_per_s": {str(k): round(v) for k, v in h2d.items()},
        "d2h_bytes_per_s": {str(k): round(v) for k, v in d2h.items()},
        "rtt_s": rtt,
        # sustained figure: the LARGEST transfer's bandwidth (small
        # sizes are RTT/warmup-dominated and can read as outliers)
        "bw_bytes_per_s": h2d[sizes[-1]],
    }


# -------------------------------------------------------- workloads --


def _fa_stream(n, seed=0):
    from delta_tpu.utils.synth import fa_history

    pk, dk, ver, order, add, _size = fa_history(
        n, seed=seed, dv_frac=0.02)
    return pk, dk, ver, order, add


def wl_replay(n, device):
    """Full replay: device product path (FA-coded transfer) vs numpy
    lexsort last-wins."""
    from delta_tpu.ops.replay import replay_select

    pk, dk, ver, order, add = _fa_stream(n)

    def dev():
        live, _ = replay_select([pk, dk], ver, order, add,
                                device=device)
        return int(live.sum())

    dev()  # compile + warm
    t_dev = _best(dev, k=2)

    def host():
        key = pk.astype(np.uint64) * np.uint64(4) + dk
        shift = np.uint64(max(1, int(n - 1).bit_length()))
        k = (key << shift) | np.arange(n, dtype=np.uint64)
        srt = np.sort(k)
        kk = srt >> shift
        boundary = np.empty(n, bool)
        boundary[:-1] = kk[:-1] != kk[1:]
        boundary[-1] = True
        idx = (srt & np.uint64((1 << int(shift)) - 1))[boundary]
        return int(add[idx.astype(np.int64)].sum())

    live_h = host()
    t_host = _best(host, k=2)
    assert dev() == live_h
    # device compute isolated: resident operands (raw key lane)
    import jax
    import jax.numpy as jnp
    from jax import lax

    key = (pk.astype(np.uint32) << np.uint32(2)) | dk
    dkey = jax.device_put(key, device)
    dadd = jax.device_put(add, device)

    @jax.jit
    def kern(key, addv):
        iota = jnp.arange(key.shape[0], dtype=jnp.uint32)
        s_key, s_add = lax.sort(
            (key, addv.astype(jnp.uint8)), num_keys=1, is_stable=True)
        is_last = jnp.concatenate(
            [s_key[:-1] != s_key[1:], jnp.ones((1,), bool)])
        return jnp.sum((is_last & (s_add == 1)).astype(jnp.int32))

    kern(dkey, dadd).block_until_ready()
    t_comp = _best(lambda: kern(dkey, dadd).block_until_ready(), k=3)
    bytes_moved = n * 1.0 + n // 8  # FA coding ~1B/row + winner words
    return {"n": n, "t_device_s": t_dev, "t_host_s": t_host,
            "t_device_compute_s": t_comp,
            "bytes_transferred_est": int(bytes_moved),
            "device_wins": t_dev < t_host}


def wl_blockwise(n, device):
    """Blockwise (>HBM) replay with resident bitset vs the same numpy
    lexsort (the host has no memory pressure at these sizes, so this
    is a fair strongest-host baseline)."""
    from delta_tpu.ops.replay_blockwise import replay_select_blockwise

    pk, dk, ver, order, add = _fa_stream(n, seed=1)

    def dev():
        live, _ = replay_select_blockwise(
            [pk, dk], ver, order, add, device=device)
        return int(live.sum())

    got = dev()
    t_dev = _best(dev, k=2)

    def host():
        key = pk.astype(np.uint64) * np.uint64(4) + dk
        shift = np.uint64(max(1, int(n - 1).bit_length()))
        k = (key << shift) | np.arange(n, dtype=np.uint64)
        srt = np.sort(k)
        kk = srt >> shift
        boundary = np.empty(n, bool)
        boundary[:-1] = kk[:-1] != kk[1:]
        boundary[-1] = True
        idx = (srt & np.uint64((1 << int(shift)) - 1))[boundary]
        return int(add[idx.astype(np.int64)].sum())

    assert host() == got
    t_host = _best(host, k=2)
    # isolated compute: one resident block step x number of blocks
    import jax
    import jax.numpy as jnp

    from delta_tpu.ops.replay import _PAD_KEY, pad_bucket
    from delta_tpu.ops.replay_blockwise import (
        DEFAULT_BLOCK_ROWS,
        _block_kernel_impl,
    )

    m = pad_bucket(min(DEFAULT_BLOCK_ROWS, n))
    n_blocks = -(-n // m)
    # densify exactly like the real blockwise path: the kernel's seen
    # bitset is sized to the unique-key space, so raw sparse keys would
    # clamp out of range and measure a degenerate access pattern
    wide = (pk.astype(np.uint64) << np.uint64(2)) | dk
    _, dense = np.unique(wide, return_inverse=True)
    key32 = dense.astype(np.uint32)[:m]
    blk = np.full(m, _PAD_KEY, np.uint32)
    blk[:len(key32)] = key32
    n_words = -(-(int(key32.max()) + 1) // 32)
    step = jax.jit(lambda seen, keys: _block_kernel_impl(
        seen, keys, jnp.int32(m), m))
    seen0 = jax.device_put(
        jnp.zeros((pad_bucket(max(n_words, 1024)),), jnp.uint32),
        device)
    dblk = jax.device_put(blk, device)
    step(seen0, dblk)[0].block_until_ready()
    t_block = _best(
        lambda: step(seen0, dblk)[0].block_until_ready(), k=3)
    t_comp = t_block * n_blocks
    bytes_moved = n * 4.0 + n // 8  # u32 key blocks + winner words
    return {"n": n, "t_device_s": t_dev, "t_host_s": t_host,
            "t_device_compute_s": t_comp,
            "bytes_transferred_est": int(bytes_moved),
            "device_wins": t_dev < t_host}


def wl_merge_join(n, device):
    """MERGE match-finding: device sort/segment equi-join vs numpy
    argsort + searchsorted."""
    import jax

    from delta_tpu.ops.join import equi_join_codes

    rng = np.random.default_rng(2)
    target = rng.permutation(np.arange(n, dtype=np.uint32))
    source = rng.integers(0, n * 2, n // 2).astype(np.uint32)

    def dev():
        match_src, _n_multi, _sm = equi_join_codes(
            target, source, device=device)
        return int((match_src >= 0).sum())

    got = dev()
    t_dev = _best(dev, k=2)

    def host():
        ss = np.sort(source)
        pos = np.searchsorted(ss, target)
        pos_c = np.clip(pos, 0, len(ss) - 1)
        hit = ss[pos_c] == target
        return int(hit.sum())

    assert host() == got
    t_host = _best(host, k=2)
    # device compute isolated with resident operands
    import jax.numpy as jnp

    dt = jax.device_put(target, device)
    ds = jax.device_put(source, device)

    @jax.jit
    def kern(t, s):
        ss = jnp.sort(s)
        pos = jnp.searchsorted(ss, t)
        pos_c = jnp.clip(pos, 0, s.shape[0] - 1)
        return jnp.sum((ss[pos_c] == t).astype(jnp.int32))

    kern(dt, ds).block_until_ready()
    t_comp = _best(lambda: kern(dt, ds).block_until_ready(), k=3)
    bytes_moved = n * 8 + (n // 2) * 8 + n * 4
    return {"n": n, "t_device_s": t_dev, "t_host_s": t_host,
            "t_device_compute_s": t_comp,
            "bytes_transferred_est": int(bytes_moved),
            "device_wins": t_dev < t_host}


def wl_sql_groupby(n, device):
    """SQL GROUP BY spine: device segment reduce (sum+count over dense
    group codes, `ops/sqlops.py::GroupAggregator`) vs the displaced
    substrate — pandas groupby — AND the strongest numpy formulation
    (np.bincount weighted sums), reported against the stronger of the
    two."""
    import jax
    import pandas as pd

    from delta_tpu.ops import sqlops

    rng = np.random.default_rng(11)
    G = max(n // 100, 16)
    codes = rng.integers(0, G, n).astype(np.int32)
    v = rng.standard_normal(n) * 100.0
    valid = np.ones(n, bool)

    def dev():
        ga = sqlops.GroupAggregator(codes, G, device=device)
        s, c = ga.reduce(v, valid, "sum")
        return float(s.sum()), int(c.sum())

    got = dev()
    t_dev = _best(dev, k=2)

    def host_pandas():
        g = pd.Series(v).groupby(codes)
        s = g.sum()
        c = g.count()
        return float(s.sum()), int(c.sum())

    def host_numpy():
        s = np.bincount(codes, weights=v, minlength=G)
        c = np.bincount(codes, minlength=G)
        return float(s.sum()), int(c.sum())

    hp = host_pandas()
    assert abs(hp[0] - got[0]) < 1e-6 * max(1, abs(got[0]))
    assert hp[1] == got[1]
    t_pandas = _best(host_pandas, k=2)
    t_numpy = _best(host_numpy, k=2)
    t_host = min(t_pandas, t_numpy)

    # isolated compute: resident padded operands through the jit kernel
    npad = sqlops.pad_bucket(n)
    n_seg = sqlops.pad_bucket(G + 1, min_bucket=256)
    cp = np.full(npad, n_seg - 1, np.int32)
    cp[:n] = codes
    vp = np.zeros(npad, np.float64)
    vp[:n] = v
    mp = np.zeros(npad, bool)
    mp[:n] = valid
    dc = jax.device_put(cp, device)
    dv = jax.device_put(vp, device)
    dm = jax.device_put(mp, device)

    def comp():
        s, c = sqlops._segagg_kernel(dc, dv, dm, op="sum", n_seg=n_seg)
        s.block_until_ready()

    comp()
    t_comp = _best(comp, k=3)
    bytes_moved = n * (4 + 8 + 1) + G * 16
    return {"n": n, "t_device_s": t_dev, "t_host_s": t_host,
            "t_host_pandas_s": t_pandas, "t_host_numpy_s": t_numpy,
            "t_device_compute_s": t_comp,
            "bytes_transferred_est": int(bytes_moved),
            "device_wins": t_dev < t_host}


def wl_sql_join(n, device):
    """SQL many-to-many equi-join spine: device sort + host pair
    expansion (`ops/sqlops.py::join_pairs`) vs pandas merge (the
    displaced substrate)."""
    import pandas as pd

    import jax

    from delta_tpu.ops import sqlops

    rng = np.random.default_rng(12)
    nl, nr = n, n // 2
    lk = rng.integers(0, n, nl).astype(np.uint32)
    rk = rng.integers(0, n, nr).astype(np.uint32)

    def dev():
        li, ri = sqlops.join_pairs(lk, rk, how="inner", device=device)
        return len(li)

    got = dev()
    t_dev = _best(dev, k=2)

    left = pd.DataFrame({"k": lk})
    right = pd.DataFrame({"k": rk})

    def host():
        return len(left.merge(right, on="k", how="inner"))

    assert host() == got
    t_host = _best(host, k=2)

    # isolated compute: the combined sort on resident operands
    npad = sqlops.pad_bucket(nl + nr)
    codes = np.full(npad, 0xFFFFFFFF, np.uint32)
    codes[:nl] = lk
    codes[nl:nl + nr] = rk
    side = np.zeros(npad, np.uint32)
    side[nl:] = 1
    iota = np.arange(npad, dtype=np.int64)
    dc = jax.device_put(codes, device)
    ds = jax.device_put(side, device)
    di = jax.device_put(iota, device)

    def comp():
        out = sqlops._join_sort_kernel(dc, ds, di)
        out[0].block_until_ready()

    comp()
    t_comp = _best(comp, k=3)
    bytes_moved = npad * (4 + 4 + 8) * 2  # up + sorted lanes down
    return {"n": n, "t_device_s": t_dev, "t_host_s": t_host,
            "t_device_compute_s": t_comp,
            "bytes_transferred_est": int(bytes_moved),
            "device_wins": t_dev < t_host}


def wl_sql_sort(n, device):
    """SQL ORDER BY / window sort spine: device stable multi-lane sort
    permutation vs numpy lexsort (stronger than pandas sort_values)."""
    import jax

    from delta_tpu.ops import sqlops

    rng = np.random.default_rng(13)
    a = rng.integers(0, 1000, n).astype(np.int64)
    b = rng.standard_normal(n)

    def dev():
        return len(sqlops.sort_permutation([a, b], device=device))

    dev()
    t_dev = _best(dev, k=2)

    def host():
        return len(np.lexsort((b, a)))

    t_host = _best(host, k=2)
    assert np.array_equal(sqlops.sort_permutation([a, b], device=device),
                          np.lexsort((b, a)))

    npad = sqlops.pad_bucket(n)
    ap = np.full(npad, np.iinfo(np.int64).max, np.int64)
    ap[:n] = a
    bp = np.full(npad, np.inf, np.float64)
    bp[:n] = b
    iota = np.arange(npad, dtype=np.int64)
    da = jax.device_put(ap, device)
    db = jax.device_put(bp, device)
    di = jax.device_put(iota, device)

    def comp():
        sqlops._sort_kernel((da, db, di), num_keys=2) \
            .block_until_ready()

    comp()
    t_comp = _best(comp, k=3)
    bytes_moved = n * (8 + 8) + n * 8
    return {"n": n, "t_device_s": t_dev, "t_host_s": t_host,
            "t_device_compute_s": t_comp,
            "bytes_transferred_est": int(bytes_moved),
            "device_wins": t_dev < t_host}


def wl_page_decode(n, device):
    """Checkpoint Parquet page decode: the thrift/page split + Pallas
    bit-unpack + dictionary-gather path (log/page_decode.py) vs
    pyarrow's C++ reader on the same single column."""
    import tempfile

    import pyarrow as pa
    import pyarrow.parquet as pq

    from delta_tpu.log.page_decode import read_checkpoint_column

    rng = np.random.default_rng(21)
    vals = rng.integers(0, 60_000, n)  # dictionary-encodable domain
    path = tempfile.mktemp(suffix=".parquet")
    pq.write_table(pa.table({"x": pa.array(vals, pa.int64())}), path)

    def dev():
        v, ok = read_checkpoint_column(path, "x", device=device)
        return int(v[ok].sum())

    got = dev()
    t_dev = _best(dev, k=2)

    def host():
        return int(pq.read_table(path, columns=["x"])
                   .column("x").to_numpy().sum())

    assert host() == got
    t_host = _best(host, k=2)

    # isolated compute: the unpack kernel on resident padded words
    import jax

    from delta_tpu.ops import sqlops  # noqa: F401  (x64 on)
    from delta_tpu.ops.pallas_kernels import (
        _TILE,
        unpack_bitpacked_tiled,
    )

    w = 16
    groups = -(-n // 32)
    padded = -(-groups // _TILE) * _TILE
    words = rng.integers(0, 1 << 32, (w, padded), dtype=np.uint64)         .astype(np.uint32)
    # pin x32: Mosaic lowers the kernel with i32 grid indexing and a
    # prior sql workload flipped global x64 in this process
    with jax.enable_x64(False):
        dw = jax.device_put(words, device)
        unpack_bitpacked_tiled(dw, w).block_until_ready()
        t_comp = _best(
            lambda: unpack_bitpacked_tiled(dw, w).block_until_ready(),
            k=3)
    bytes_moved = padded * w * 4 + n * 4
    os.unlink(path)
    return {"n": n, "t_device_s": t_dev, "t_host_s": t_host,
            "t_device_compute_s": t_comp,
            "bytes_transferred_est": int(bytes_moved),
            "device_wins": t_dev < t_host}


# ------------------------------------------------------- cost model --


def model(link, wl, k_rtts=4):
    """Predicted wall on the measured link and projected wall on PCIe
    from the same isolated compute + byte counts."""
    bw = link["bw_bytes_per_s"]
    rtt = link["rtt_s"]
    comp = wl.get("t_device_compute_s", 0.0)
    b = wl["bytes_transferred_est"]
    return {
        "predicted_tunnel_s": b / bw + k_rtts * rtt + comp,
        "projected_pcie_s": b / PCIE_BW_BYTES_S + k_rtts * PCIE_RTT_S
        + comp,
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="DEVICE_MERIT.json")
    ap.add_argument("--replay-rows", type=int, default=30_000_000)
    ap.add_argument("--blockwise-rows", type=int, default=100_000_000)
    ap.add_argument("--join-rows", type=int, default=10_000_000)
    ap.add_argument("--sql-rows", type=int, default=10_000_000)
    args = ap.parse_args()

    import jax

    device = jax.devices()[0]
    print(f"device: {device}", file=sys.stderr)
    from delta_tpu.utils.alloc import tune_allocator

    tune_allocator()

    link = measure_link(device)
    print(f"link: bw={link['bw_bytes_per_s'] / 1e6:.1f}MB/s "
          f"rtt={link['rtt_s'] * 1e3:.1f}ms", file=sys.stderr)

    out = {"device": str(device), "link": link, "workloads": {}}
    for name, fn, n in (
            ("replay_fa", wl_replay, args.replay_rows),
            ("blockwise_replay", wl_blockwise, args.blockwise_rows),
            ("merge_join", wl_merge_join, args.join_rows),
            ("sql_groupby", wl_sql_groupby, args.sql_rows),
            ("sql_join", wl_sql_join, args.sql_rows),
            ("sql_sort", wl_sql_sort, args.sql_rows),
            ("page_decode", wl_page_decode, args.sql_rows)):
        print(f"== {name} @ {n} rows", file=sys.stderr)
        try:
            wl = fn(n, device)
        except Exception as exc:
            # record the failure honestly (e.g. a transient remote-
            # compile 500 over the tunnel) instead of losing the run
            import traceback

            traceback.print_exc()
            out["workloads"][name] = {"n": n, "error": str(exc)[:300]}
            continue
        wl["model"] = model(link, wl)
        wl["projected_pcie_wins"] = (
            wl["model"]["projected_pcie_s"] < wl["t_host_s"])
        out["workloads"][name] = wl
        print(f"  device {wl['t_device_s']:.2f}s vs host "
              f"{wl['t_host_s']:.2f}s -> "
              f"{'DEVICE WINS' if wl['device_wins'] else 'host wins'}; "
              f"pcie projection {wl['model']['projected_pcie_s']:.2f}s",
              file=sys.stderr)

    out["any_device_win_measured"] = any(
        w.get("device_wins") for w in out["workloads"].values())
    with open(args.out, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps({"metric": "device_merit_wins",
                      "value": sum(bool(w.get("device_wins"))
                                   for w in out["workloads"].values()),
                      "unit": "workloads",
                      "vs_baseline": 0.0}))


if __name__ == "__main__":
    main()

"""Benchmark driver: `python -m benchmarks.run --benchmark replay --scale small`.

`--benchmark all` runs every workload; the JSON report lands in
`--report-dir` (default the workdir).
"""

from __future__ import annotations

import argparse
import os
import sys


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--benchmark", default="replay")
    ap.add_argument("--scale", default="small",
                    choices=["smoke", "small", "medium", "large", "full"])
    ap.add_argument("--workdir", default="/tmp/delta_tpu_bench")
    ap.add_argument("--report-dir", default=None)
    args = ap.parse_args()

    from benchmarks.workloads import BENCHMARKS

    names = list(BENCHMARKS) if args.benchmark == "all" else [args.benchmark]
    os.makedirs(args.workdir, exist_ok=True)
    report_dir = args.report_dir or args.workdir
    os.makedirs(report_dir, exist_ok=True)
    for name in names:
        print(f"== {name} ({args.scale})", file=sys.stderr)
        bench = BENCHMARKS[name](scale=args.scale, workdir=args.workdir)
        report = bench.run()
        out = os.path.join(report_dir, f"report_{name}_{args.scale}.json")
        with open(out, "w") as f:
            f.write(report.to_json())
        print(f"report -> {out}", file=sys.stderr)


if __name__ == "__main__":
    main()
